"""Tests for the on-device detect path and its host-side glue."""

import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.data import (
    CocoDataset,
    PipelineConfig,
    build_pipeline,
    make_synthetic_coco,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import evaluate_detections
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    coco_gt_from_dataset,
    detections_to_coco,
    make_detect_fn,
    run_coco_eval,
)
from batchai_retinanet_horovod_coco_tpu.ops.nms import Detections


class TestDetectionsToCoco:
    def test_rescale_and_format(self):
        det = Detections(
            boxes=jnp.array([[[10.0, 20.0, 30.0, 60.0], [0.0, 0.0, 0.0, 0.0]]]),
            scores=jnp.array([[0.9, -1e9]]),
            labels=jnp.array([[1, -1]], dtype=jnp.int32),
            valid=jnp.array([[True, False]]),
        )
        out = detections_to_coco(
            det,
            image_ids=np.array([42]),
            scales=np.array([2.0]),  # resized = 2x original
            valid_rows=np.array([True]),
            label_to_cat_id={1: 7},
        )
        assert len(out) == 1  # invalid slot dropped
        r = out[0]
        assert r["image_id"] == 42
        assert r["category_id"] == 7
        # boxes halved back to original coords, xywh format
        assert r["bbox"] == pytest.approx([5.0, 10.0, 10.0, 20.0])
        assert r["score"] == pytest.approx(0.9)

    def test_clamp_to_original_image_bounds(self):
        # Bucket padding lets device boxes extend past the true image; with
        # image_sizes they must be clamped, and padding-only boxes dropped.
        det = Detections(
            boxes=jnp.array(
                [[[90.0, 10.0, 140.0, 40.0], [120.0, 5.0, 160.0, 30.0]]]
            ),
            scores=jnp.array([[0.8, 0.4]]),
            labels=jnp.array([[0, 0]], dtype=jnp.int32),
            valid=jnp.array([[True, True]]),
        )
        out = detections_to_coco(
            det,
            image_ids=np.array([7]),
            scales=np.array([1.0]),
            valid_rows=np.array([True]),
            label_to_cat_id={0: 1},
            image_sizes={7: (100, 50)},  # true image is 100 wide
        )
        assert len(out) == 1  # box fully inside padding (x>=120) dropped
        assert out[0]["bbox"] == pytest.approx([90.0, 10.0, 10.0, 30.0])

    def test_padding_rows_skipped(self):
        det = Detections(
            boxes=jnp.zeros((2, 1, 4)),
            scores=jnp.ones((2, 1)),
            labels=jnp.zeros((2, 1), dtype=jnp.int32),
            valid=jnp.ones((2, 1), dtype=bool),
        )
        out = detections_to_coco(
            det,
            image_ids=np.array([1, 0]),
            scales=np.array([1.0, 1.0]),
            valid_rows=np.array([True, False]),
            label_to_cat_id={0: 1},
        )
        assert [r["image_id"] for r in out] == [1]


class TestGtExtraction:
    def test_gt_round_trip_is_perfect_ap(self, tmp_path):
        make_synthetic_coco(str(tmp_path), num_images=4, num_classes=2, seed=3)
        ds = CocoDataset(str(tmp_path / "instances_train.json"), str(tmp_path / "train"))
        gts, img_ids = coco_gt_from_dataset(ds)
        dts = [{**g, "score": 0.9} for g in gts]
        stats = evaluate_detections(gts, dts, img_ids=img_ids)
        assert stats["AP"] == pytest.approx(1.0)


@pytest.mark.slow
class TestEndToEnd:
    def test_run_coco_eval_smoke(self, tmp_path, tiny_model_and_state):
        """Untrained model through the FULL eval path → finite stats."""
        model, state = tiny_model_and_state
        make_synthetic_coco(
            str(tmp_path), num_images=4, num_classes=3, image_size=(128, 128)
        )
        ds = CocoDataset(str(tmp_path / "instances_train.json"), str(tmp_path / "train"))
        cfg = PipelineConfig(
            batch_size=2,
            buckets=((128, 128),),
            min_side=128,
            max_side=128,
            max_gt=8,
            shuffle=False,
        )
        batches = build_pipeline(ds, cfg, train=False)
        stats = run_coco_eval(state, model, ds, batches)
        assert set(stats) >= {"AP", "AP50", "AR100"}
        assert 0.0 <= stats["AP"] <= 1.0 or stats["AP"] == -1.0

    def test_detect_fn_shapes(self, tiny_model_and_state):
        model, state = tiny_model_and_state
        fn = make_detect_fn(model, (64, 64))
        det = fn(state, jnp.zeros((2, 64, 64, 3)))
        assert det.boxes.shape == (2, 300, 4)
        assert det.scores.shape == (2, 300)
        assert det.labels.shape == (2, 300)
        assert det.valid.shape == (2, 300)
