"""Host input-pipeline saturation bench: decode+resize+assemble imgs/s vs workers.

The input pipeline is the classic scaling-efficiency killer for detection
workloads (SURVEY.md §7.3 part 6): at pod scale every host must decode
enough images per second to feed its chips (~4 chips/host on v5e, so
4 x chip-throughput imgs/s/host).  This bench measures the REAL pipeline —
JPEG decode, multiscale resize to the flagship buckets, pad/assemble,
target-free (targets are computed on device) — for BOTH producers:

- ``threads``: the in-process ThreadPoolExecutor path, swept over thread
  counts.  Round 5 showed it plateaus at ~2 workers (PIL JPEG decode holds
  the GIL) at ~37 imgs/s/host — below one chip's ~67 imgs/s demand.
- ``procs``: the multiprocess shared-memory path (data/shm_pipeline.py),
  swept over process counts — the GIL-free producer this plateau motivated.

It prints one JSON line:

  {"metric": "host_pipeline_images_per_sec", "value": <best overall>,
   "threads": {"1": ..., ...}, "procs": {"1": ..., ...},
   "best_threads": ..., "best_procs": ..., "procs_speedup": ...,
   "cores_available": N, ...}

Run it on the actual pod host class to validate the scaling argument in
PARITY.md; the committed PIPEBENCH.json records this dev box's numbers
(note its core count — per-core throughput is the portable figure).

``--check`` mirrors bench.py's bench-check tripwire: the measured best must
stay within NOISE_BAND_PCT of the committed PIPEBENCH.json value (exit 1 on
regression).  A crashed decode worker surfaces as the sweep point's
``error`` string rather than killing (or hanging) the whole bench.

Usage: python bench_pipeline.py [--images N] [--batches N]
         [--workers 1,2,4,8] [--procs 1,2,4] [--check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

# Same tripwire policy as bench.py's bench-check, with a much wider band:
# the host pipeline is scheduler-noisy in a way the device bench is not
# (process spawn, page-cache state, sibling load on shared/sandboxed dev
# boxes — full-run best-of-2 values were observed ranging ~90-106 imgs/s
# on the committed box).  The tripwire exists to catch structural
# regressions (a serialized producer, a quadratic assembly), which cost
# 2x+, not to police single-digit drift.
NOISE_BAND_PCT = 15.0


def run_one(
    data_dir: str,
    num_workers: int,
    batches: int,
    batch_size: int,
    num_worker_procs: int = 0,
) -> float:
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
    )
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import default_buckets

    dataset = CocoDataset(
        os.path.join(data_dir, "instances_train.json"),
        os.path.join(data_dir, "train"),
    )
    pipe = build_pipeline(
        dataset,
        PipelineConfig(
            batch_size=batch_size,
            buckets=default_buckets(800, 1344),
            min_side=800,
            max_side=1344,
            max_gt=100,
            num_workers=num_workers,
            num_worker_procs=num_worker_procs,
            seed=0,
        ),
        train=True,
    )
    try:
        it = iter(pipe)
        next(it)  # warmup: worker pool spin-up + first-batch latency
        t0 = time.perf_counter()
        n = 0
        for _ in range(batches):
            batch = next(it)
            n += batch.images.shape[0]
        dt = time.perf_counter() - t0
    finally:
        pipe.close()
    return n / dt


def sweep(
    data_dir: str, counts: list[int], batches: int, batch_size: int,
    procs: bool, repeats: int = 2,
) -> dict[str, float | str]:
    """One producer's sweep; a crashed/wedged worker becomes that point's
    ``error`` string instead of aborting the other points.

    Each point takes the BEST of ``repeats`` runs: on shared/sandboxed dev
    boxes a single run can lose 2x to transient sibling load, and the
    quantity of interest is the producer's capacity, not the box's weather.
    """
    out: dict[str, float | str] = {}
    for c in counts:
        rates = []
        err = None
        for _ in range(max(1, repeats)):
            try:
                rates.append(run_one(
                    data_dir, 0 if procs else c, batches, batch_size,
                    num_worker_procs=c if procs else 0,
                ))
            except RuntimeError as e:
                err = e
        out[str(c)] = round(max(rates), 2) if rates else f"error: {err}"
    return out


def _ceiling_worker(data_dir: str, q) -> None:
    """One fully independent decode loop — no queues, no shared memory, no
    coordination.  N of these concurrently measure the HARDWARE's parallel
    decode capacity, the number the coordinated procs path is fairly judged
    against (vCPUs on shared/sandboxed dev boxes often deliver far less
    than cores_available x single-core throughput for this memory-bound
    workload)."""
    try:
        import cv2

        cv2.setNumThreads(1)
    except Exception:
        pass
    import time as _time

    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        bucket_for_source,
        default_buckets,
        example_rng,
        load_example,
    )

    ds = CocoDataset(
        os.path.join(data_dir, "instances_train.json"),
        os.path.join(data_dir, "train"),
    )
    cfg = PipelineConfig(
        batch_size=8, buckets=default_buckets(800, 1344), min_side=800,
        max_side=1344, max_gt=100, seed=0,
    )

    def one_pass():
        for i, r in enumerate(ds.records):
            b = bucket_for_source(r.height, r.width, 800, 1344, cfg.buckets)
            load_example(ds, r, cfg, example_rng(cfg, True, 0, i), b)

    one_pass()  # warm (page cache, imports)
    t0 = _time.perf_counter()
    one_pass()
    q.put(len(ds.records) / (_time.perf_counter() - t0))


def measure_ceiling(data_dir: str, nprocs: int) -> float:
    """Aggregate imgs/s of ``nprocs`` INDEPENDENT decode processes."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue(maxsize=nprocs)  # exactly one result per worker
    procs = [
        # lint: watchdog-coverage: short-lived ceiling probe workers — the
        # bounded get + liveness loop below reaps crashes within 5 s.
        ctx.Process(target=_ceiling_worker, args=(data_dir, q))
        for _ in range(nprocs)
    ]
    for p in procs:
        p.start()
    # Bounded get + liveness: a crashed worker (OOM, bad data dir) must
    # degrade the measurement, never hang the bench.
    import queue as _queue

    total = 0.0
    received = 0
    deadline = time.monotonic() + 300.0
    while received < len(procs) and time.monotonic() < deadline:
        try:
            total += q.get(timeout=5.0)
            received += 1
        except _queue.Empty:
            if all(p.exitcode is not None for p in procs) and q.empty():
                break  # some worker died without reporting
    if received < len(procs):
        print(
            f"pipebench: {len(procs) - received} ceiling worker(s) died "
            "without reporting; ceiling reflects the survivors",
            file=sys.stderr,
        )
    for p in procs:
        p.join(timeout=10.0)
        if p.is_alive():
            p.terminate()
    return total


def _committed_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PIPEBENCH.json")


def check_against_committed(value: float) -> int:
    """bench.py's bench-check, for the host pipeline: the committed
    PIPEBENCH.json best minus the noise band is the floor; exit 1 below it."""
    with open(_committed_path()) as f:
        committed = float(json.load(f)["value"])
    floor = committed * (1 - NOISE_BAND_PCT / 100)
    ok = value >= floor
    verdict = "ok" if ok else "REGRESSION"
    print(
        f"pipebench-check: measured {value:.2f} vs committed {committed:.2f} "
        f"(floor {floor:.2f} = -{NOISE_BAND_PCT}%): {verdict}"
    )
    return 0 if ok else 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=64,
                    help="synthetic JPEG count (COCO-typical 640x480)")
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--workers", default="1,2,4,8",
                    help="thread-pool sweep (comma list; empty to skip)")
    ap.add_argument("--procs", default="1,2,4",
                    help="multiprocess shm-pipeline sweep (comma list; "
                         "empty to skip)")
    ap.add_argument("--repeats", type=int, default=2,
                    help="runs per sweep point; the best is reported")
    ap.add_argument("--check", action="store_true",
                    help="compare the measured best against the committed "
                         "PIPEBENCH.json noise band; exit 1 on regression")
    ap.add_argument("--data-dir", default=None,
                    help="existing COCO-format dir (default: synthesize)")
    args = ap.parse_args()

    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    tmp = None
    data_dir = args.data_dir
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pipebench_")
        data_dir = tmp.name
        # COCO-typical source resolution so decode+resize cost is realistic.
        make_synthetic_coco(
            data_dir, num_images=args.images, num_classes=8,
            image_size=(480, 640), seed=0, split="train",
        )

    def parse_counts(text: str) -> list[int]:
        return [int(x) for x in text.split(",") if x.strip()]

    threads = sweep(data_dir, parse_counts(args.workers), args.batches,
                    args.batch_size, procs=False, repeats=args.repeats)
    procs = sweep(data_dir, parse_counts(args.procs), args.batches,
                  args.batch_size, procs=True, repeats=args.repeats)

    def best(d: dict) -> float:
        vals = [v for v in d.values() if isinstance(v, (int, float))]
        return max(vals) if vals else 0.0

    best_threads, best_procs = best(threads), best(procs)
    value = max(best_threads, best_procs)
    cores = len(os.sched_getaffinity(0))
    proc_counts = parse_counts(args.procs)
    ceiling = (
        round(measure_ceiling(data_dir, max(proc_counts)), 2)
        if proc_counts else None
    )
    print(json.dumps({
        "metric": "host_pipeline_images_per_sec",
        "value": value,
        "unit": "images/sec/host",
        "threads": threads,
        "procs": procs,
        "best_threads": best_threads,
        "best_procs": best_procs,
        # The headline ratio: how much the GIL-free producer buys on THIS
        # box (compare like-for-like in one run; absolute rates depend on
        # core count and sibling load).
        "procs_speedup": round(best_procs / best_threads, 2)
        if best_threads and best_procs else None,
        # What the hardware gives N INDEPENDENT decode processes (no
        # coordination): the fair denominator for the procs path.  Shared/
        # sandboxed dev boxes can deliver far below cores x single-proc for
        # this memory-bound workload, in which case NO producer design can
        # beat threads by much — judge the procs path by its efficiency
        # against this ceiling, and the threads-vs-procs gap by core count.
        "independent_decode_ceiling": ceiling,
        "procs_efficiency_vs_ceiling": round(best_procs / ceiling, 2)
        if ceiling else None,
        "cores_available": cores,
        "per_core": round(value / max(cores, 1), 2),
        "source_resolution": "640x480 JPEG",
        "target": "800x1344-bucketed multiscale resize + pad",
    }))
    if tmp is not None:
        tmp.cleanup()
    if args.check:
        raise SystemExit(check_against_committed(value))


if __name__ == "__main__":
    main()
