"""Host input-pipeline saturation bench: decode+resize+assemble imgs/s vs workers.

The input pipeline is the classic scaling-efficiency killer for detection
workloads (SURVEY.md §7.3 part 6): at pod scale every host must decode
enough images per second to feed its chips (~4 chips/host on v5e, so
4 x chip-throughput imgs/s/host).  This bench measures the REAL pipeline —
JPEG decode, multiscale resize to the flagship buckets, pad/assemble,
target-free (targets are computed on device) — against worker count, and
prints one JSON line:

  {"metric": "host_pipeline_images_per_sec", "value": <best>,
   "per_worker": {"1": ..., "2": ..., ...}, "cores_available": N, ...}

Run it on the actual pod host class to validate the scaling argument in
PARITY.md; the committed PIPEBENCH.json records this dev box's numbers
(note its core count — per-core throughput is the portable figure).

Usage: python bench_pipeline.py [--images N] [--batches N] [--workers 1,2,4,8]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def run_one(data_dir: str, num_workers: int, batches: int, batch_size: int) -> float:
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
    )
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import default_buckets

    dataset = CocoDataset(
        os.path.join(data_dir, "instances_train.json"),
        os.path.join(data_dir, "train"),
    )
    pipe = build_pipeline(
        dataset,
        PipelineConfig(
            batch_size=batch_size,
            buckets=default_buckets(800, 1344),
            min_side=800,
            max_side=1344,
            max_gt=100,
            num_workers=num_workers,
            seed=0,
        ),
        train=True,
    )
    it = iter(pipe)
    next(it)  # warmup: thread pool spin-up + first-batch latency
    t0 = time.perf_counter()
    n = 0
    for _ in range(batches):
        batch = next(it)
        n += batch.images.shape[0]
    dt = time.perf_counter() - t0
    pipe.close()
    return n / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=64,
                    help="synthetic JPEG count (COCO-typical 640x480)")
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--workers", default="1,2,4,8")
    ap.add_argument("--data-dir", default=None,
                    help="existing COCO-format dir (default: synthesize)")
    args = ap.parse_args()

    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    tmp = None
    data_dir = args.data_dir
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pipebench_")
        data_dir = tmp.name
        # COCO-typical source resolution so decode+resize cost is realistic.
        make_synthetic_coco(
            data_dir, num_images=args.images, num_classes=8,
            image_size=(480, 640), seed=0, split="train",
        )

    per_worker: dict[str, float] = {}
    for w in [int(x) for x in args.workers.split(",")]:
        per_worker[str(w)] = round(run_one(
            data_dir, w, args.batches, args.batch_size
        ), 2)

    best = max(per_worker.values())
    cores = len(os.sched_getaffinity(0))
    print(json.dumps({
        "metric": "host_pipeline_images_per_sec",
        "value": best,
        "unit": "images/sec/host",
        "per_worker": per_worker,
        "cores_available": cores,
        "per_core": round(best / max(cores, 1), 2),
        "source_resolution": "640x480 JPEG",
        "target": "800x1344-bucketed multiscale resize + pad",
    }))
    if tmp is not None:
        tmp.cleanup()


if __name__ == "__main__":
    main()
