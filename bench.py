"""Benchmark: flagship train-step throughput, printed as ONE JSON line.

Measures images/sec/chip for the full jitted SPMD training step (forward,
on-device target assignment, focal + smooth-L1 losses, backward, optimizer
update) on RetinaNet ResNet-50-FPN at the reference's flagship resolution
bucket (800x1344, BASELINE.json:10), bf16 compute.

``vs_baseline``: the reference's own throughput was never recorded
(BASELINE.json "published": {}, see BASELINE.md), so the ratio is computed
against the first recorded bench of this rebuild (BENCH_r1.json) when
present, else 1.0 — i.e. it tracks round-over-round improvement.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BUCKET = (800, 1344)
WARMUP_STEPS = 5
# 60 steps ≈ 7.5 s of device time: the tunnel's per-step dispatch jitter
# showed up as ±1 imgs/s run-to-run at 20 steps (round 3); tripling the
# window cuts that to ~±0.3 while keeping the whole bench under a minute.
MEASURE_STEPS = 60

# Peak dense bf16 TFLOP/s per chip by device kind (public spec sheets);
# used only to report MFU next to the throughput number.
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),  # Trillium
)


def _device_peak_tflops() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


def make_batch(batch_size: int, hw: tuple[int, int], max_gt: int = 100):
    rng = np.random.default_rng(0)
    h, w = hw
    gt_boxes = np.zeros((batch_size, max_gt, 4), np.float32)
    gt_labels = np.zeros((batch_size, max_gt), np.int32)
    gt_mask = np.zeros((batch_size, max_gt), bool)
    for b in range(batch_size):
        n = int(rng.integers(4, 24))
        xy = rng.uniform(0, [w - 64, h - 64], (n, 2))
        wh = rng.uniform(16, 256, (n, 2))
        gt_boxes[b, :n, 0::2] = np.stack([xy[:, 0], np.minimum(xy[:, 0] + wh[:, 0], w)], 1)
        gt_boxes[b, :n, 1::2] = np.stack([xy[:, 1], np.minimum(xy[:, 1] + wh[:, 1], h)], 1)
        gt_labels[b, :n] = rng.integers(0, 80, n)
        gt_mask[b, :n] = True
    return {
        # uint8, as the pipeline ships it (normalization runs on device and
        # fuses into the stem; measured ~2% faster than feeding f32).
        "images": jnp.asarray(
            rng.integers(0, 256, (batch_size, h, w, 3), dtype=np.uint8)
        ),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


def run_bench(batch_size: int) -> tuple[float, float | None]:
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import (
        create_train_state,
        make_train_step,
    )

    # frozen_bn is the reference's fine-tune configuration (BN frozen during
    # detection training, SURVEY.md M2) and measures ~9% faster than GN on
    # v5e (pure scale+bias fuses into the convs; GN's per-group moments are
    # extra bandwidth-bound passes).
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", norm_kind="frozen_bn"
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01, momentum=0.9), (1, *BUCKET, 3), jax.random.key(0)
    )
    step = make_train_step(model, BUCKET, 80, donate_state=True)
    batch = make_batch(batch_size, BUCKET)

    # AOT-compile once: the executable both runs the loop and reports the
    # XLA-counted FLOPs of the whole step (forward, assignment, losses,
    # backward, update) for the MFU number.
    compiled = step.lower(state, batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else None
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    for _ in range(WARMUP_STEPS):
        state, metrics = compiled(state, batch)
    # Same hard sync as the timed region: block_until_ready can return
    # early on tunneled backends, which would leak warmup work into t0.
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = compiled(state, batch)
    # Hard sync INSIDE the timed region: on tunneled backends,
    # block_until_ready on jit-call results can return before the device
    # finishes (measured 2 ms/step "throughput" on a 376 ms step); pulling
    # a scalar to host cannot lie.
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)

    ips = batch_size * MEASURE_STEPS / dt
    peak = _device_peak_tflops()
    mfu = None
    if step_flops > 0 and peak:
        achieved_tflops = step_flops * (MEASURE_STEPS / dt) / 1e12
        mfu = achieved_tflops / peak
    return ips, mfu


def first_recorded_bench() -> float | None:
    vals = {}
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            # The driver wraps the printed line under "parsed".
            if "value" not in data and "parsed" in data:
                data = data["parsed"]
            vals[int(m.group(1))] = float(data["value"])
        except Exception:
            continue
    return vals[min(vals)] if vals else None


def main() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    try:
        ips, mfu = run_bench(batch_size)
    except Exception as e:
        # Retry smaller only for HBM exhaustion; real bugs propagate.
        oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
        if batch_size <= 2 or not oom:
            raise
        print(f"# batch {batch_size} OOM; retrying at 2", flush=True)
        batch_size = 2
        ips, mfu = run_bench(batch_size)

    baseline = first_recorded_bench()
    value = round(ips, 3)
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_per_chip",
                "value": value,
                "unit": "images/sec/chip",
                "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
                "mfu": round(mfu, 4) if mfu is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
