"""Benchmark: flagship train-step throughput, printed as ONE JSON line.

Measures images/sec/chip for the full jitted SPMD training step (forward,
on-device target assignment, focal + smooth-L1 losses, backward, optimizer
update) on RetinaNet ResNet-50-FPN at the reference's flagship resolution
bucket (800x1344, BASELINE.json:10), bf16 compute.

``vs_baseline``: the reference's own throughput was never recorded
(BASELINE.json "published": {}, see BASELINE.md), so the ratio is computed
against the first recorded bench of this rebuild (BENCH_r1.json) when
present, else 1.0 — i.e. it tracks round-over-round improvement.

Bucket sweep (round 4, VERDICT r3 missing #3): the multiscale pipeline
emits TWO static buckets at the flagship 800/1333 config
(data/pipeline.default_buckets: 800x1344 landscape+near-square, 1344x800
portrait; the former third 1088x1088 bucket was proven unreachable and
dropped in round 5 — see default_buckets' docstring) — the training
wall-clock model must not assume every step runs at the landscape-bucket
rate.  By default the bench sweeps both and reports ``per_bucket``
imgs/s/chip plus ``weighted_mix``, the COCO-aspect-share-weighted rate
(shares below).  ``value`` stays the flagship 800x1344 number so
round-over-round comparisons hold.  BENCH_SWEEP=0 restores the
single-bucket bench.

In sweep mode the flagship-only line prints FIRST and the full line
(same schema + sweep keys) LAST: any consumer that reads either the
first or the last JSON line gets a valid record, even if the process is
killed mid-sweep.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BUCKET = (800, 1344)
WARMUP_STEPS = 5
# 60 steps ≈ 7.5 s of device time: the tunnel's per-step dispatch jitter
# showed up as ±1 imgs/s run-to-run at 20 steps (round 3); tripling the
# window cuts that to ~±0.3 while keeping the whole bench under a minute.
MEASURE_STEPS = 60

# Approximate share of COCO train2017 images landing in each bucket the
# flagship-config pipeline emits, keyed by the bucket's ASPECT CLASS so
# a reorder of default_buckets cannot silently swap shares: landscape
# AND square images land in 800x1344 (every resized landscape/square
# fits it), portraits (any severity) in 1344x800 — the exhaustive
# routing scan in tests/unit/test_buckets.py pins this keying against
# data/pipeline.bucket_for_source.  Shares are ESTIMATES from the
# public COCO size distribution (~640x480-class landscape dominates;
# portraits ~25%); re-derive exactly with `debug.py buckets` on the
# real annotations.
_MIX_SHARES = {"landscape": 0.77, "portrait": 0.23}


def sweep_buckets() -> tuple[tuple[tuple[int, int], float], ...]:
    """(bucket, share) pairs — shapes from the pipeline's single source
    of truth (default_buckets), so the sweep cannot silently drift from
    the shapes a training run actually compiles; only the COCO share
    estimates live here, keyed by aspect class."""
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        default_buckets,
    )

    buckets = default_buckets(800, 1333)
    # Runtime schema checks, not debug asserts: under `python -O` a bare
    # assert would vanish and a reordered default_buckets could silently
    # pair shares with the wrong shapes.
    if buckets[0] != BUCKET:
        raise RuntimeError(
            f"default_buckets(800, 1333) now leads with {buckets[0]}, not "
            f"{BUCKET} — update BUCKET (the round-over-round headline "
            "shape) and _MIX_SHARES together"
        )
    if len(buckets) == 1:
        return ((buckets[0], 1.0),)

    def aspect_class(hw: tuple[int, int]) -> str:
        h, w = hw
        return "landscape" if h < w else ("portrait" if h > w else "square")

    classes = [aspect_class(b) for b in buckets]
    if sorted(classes) != sorted(_MIX_SHARES):
        raise RuntimeError(
            f"default_buckets aspect classes {classes} no longer match the "
            f"share table {sorted(_MIX_SHARES)} — update _MIX_SHARES"
        )
    return tuple((b, _MIX_SHARES[c]) for b, c in zip(buckets, classes))


# Fewer timed steps for the non-flagship buckets: they only feed the
# weighted mix, and the sweep must stay under the driver's bench budget.
SWEEP_MEASURE_STEPS = 30

# Peak dense bf16 TFLOP/s per chip by device kind (public spec sheets);
# used only to report MFU next to the throughput number.
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),  # Trillium
)


def _device_peak_tflops() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


def make_batch(batch_size: int, hw: tuple[int, int], max_gt: int = 100):
    rng = np.random.default_rng(0)
    h, w = hw
    gt_boxes = np.zeros((batch_size, max_gt, 4), np.float32)
    gt_labels = np.zeros((batch_size, max_gt), np.int32)
    gt_mask = np.zeros((batch_size, max_gt), bool)
    for b in range(batch_size):
        n = int(rng.integers(4, 24))
        xy = rng.uniform(0, [w - 64, h - 64], (n, 2))
        wh = rng.uniform(16, 256, (n, 2))
        gt_boxes[b, :n, 0::2] = np.stack([xy[:, 0], np.minimum(xy[:, 0] + wh[:, 0], w)], 1)
        gt_boxes[b, :n, 1::2] = np.stack([xy[:, 1], np.minimum(xy[:, 1] + wh[:, 1], h)], 1)
        gt_labels[b, :n] = rng.integers(0, 80, n)
        gt_mask[b, :n] = True
    return {
        # uint8, as the pipeline ships it (normalization runs on device and
        # fuses into the stem; measured ~2% faster than feeding f32).
        "images": jnp.asarray(
            rng.integers(0, 256, (batch_size, h, w, 3), dtype=np.uint8)
        ),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


def run_bench(
    batch_size: int,
    hw: tuple[int, int] = BUCKET,
    measure_steps: int = MEASURE_STEPS,
) -> tuple[float, float | None]:
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import (
        create_train_state,
        make_train_step,
    )

    # frozen_bn is the reference's fine-tune configuration (BN frozen during
    # detection training, SURVEY.md M2) and measures ~9% faster than GN on
    # v5e (pure scale+bias fuses into the convs; GN's per-group moments are
    # extra bandwidth-bound passes).
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", norm_kind="frozen_bn"
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01, momentum=0.9), (1, *hw, 3), jax.random.key(0)
    )
    step = make_train_step(model, hw, 80, donate_state=True)
    batch = make_batch(batch_size, hw)

    # AOT-compile once: the executable both runs the loop and reports the
    # XLA-counted FLOPs of the whole step (forward, assignment, losses,
    # backward, update) for the MFU number.
    compiled = step.lower(state, batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else None
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    for _ in range(WARMUP_STEPS):
        state, metrics = compiled(state, batch)
    # Same hard sync as the timed region: block_until_ready can return
    # early on tunneled backends, which would leak warmup work into t0.
    float(metrics["loss"])

    # TWO disjoint timed windows (VERDICT r4 weak #1): the point estimate
    # alone cannot distinguish tunnel noise from a real regression; the
    # window-to-window spread is a same-run noise floor reported beside
    # the value.  Each window hard-syncs INSIDE its timed region: on
    # tunneled backends, block_until_ready on jit-call results can
    # return before the device finishes (measured 2 ms/step "throughput"
    # on a 376 ms step); pulling a scalar to host cannot lie.
    half = max(1, measure_steps // 2)
    window_rates = []
    dt_total = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(half):
            state, metrics = compiled(state, batch)
        loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        assert np.isfinite(loss)
        window_rates.append(batch_size * half / dt)
        dt_total += dt

    ips = batch_size * 2 * half / dt_total
    peak = _device_peak_tflops()
    mfu = None
    if step_flops > 0 and peak:
        achieved_tflops = step_flops * (2 * half / dt_total) / 1e12
        mfu = achieved_tflops / peak
    return ips, mfu, tuple(window_rates)


def first_recorded_bench() -> float | None:
    vals = {}
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            # The driver wraps the printed line under "parsed".
            if "value" not in data and "parsed" in data:
                data = data["parsed"]
            vals[int(m.group(1))] = float(data["value"])
        except Exception:
            continue
    return vals[min(vals)] if vals else None


def _run_with_oom_retry(batch_size, hw, measure_steps):
    try:
        return batch_size, run_bench(batch_size, hw, measure_steps)
    except Exception as e:
        # Retry smaller only for HBM exhaustion; real bugs propagate.
        oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
        if batch_size <= 2 or not oom:
            raise
        print(f"# batch {batch_size} OOM at {hw}; retrying at 2", flush=True)
        return 2, run_bench(2, hw, measure_steps)


# Regression tripwire (VERDICT r4 weak #1): `make bench-check` fails when
# the fresh flagship rate lands below the committed BUCKETBENCH.json
# number minus this band.  3% ≈ the measured tunnel noise envelope (±1
# imgs/s run-to-run at the round-3 window size, r4's −0.5% drift): the
# r4-sized drift is classified noise BY THE TOOL, a real −5% fails loudly.
NOISE_BAND_PCT = 3.0


def check_against_committed(value: float) -> int:
    """Compare a fresh flagship rate against the committed baseline;
    returns a process exit code (0 ok / 1 regression)."""
    path = os.path.join(os.path.dirname(__file__) or ".", "BUCKETBENCH.json")
    try:
        with open(path) as f:
            committed = float(
                json.load(f)["per_bucket_imgs_per_sec_per_chip"][
                    f"{BUCKET[0]}x{BUCKET[1]}"
                ]
            )
    except (OSError, KeyError, ValueError) as e:
        print(f"# bench-check: cannot read committed baseline: {e}")
        return 1
    floor = committed * (1 - NOISE_BAND_PCT / 100)
    verdict = "ok" if value >= floor else "REGRESSION"
    print(
        f"# bench-check: {value:.2f} imgs/s vs committed {committed:.2f} "
        f"(floor {floor:.2f} = -{NOISE_BAND_PCT}%): {verdict}"
    )
    return 0 if value >= floor else 1


def main() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    sweep = os.environ.get("BENCH_SWEEP", "1") not in ("", "0")

    flag_batch, (ips, mfu, windows) = _run_with_oom_retry(
        batch_size, BUCKET, MEASURE_STEPS
    )
    baseline = first_recorded_bench()
    value = round(ips, 3)
    out = {
        "metric": "train_images_per_sec_per_chip",
        "value": value,
        "unit": "images/sec/chip",
        "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # Same-run noise floor: two disjoint timed windows of the same
        # compiled step.  A cross-round delta inside this spread is noise.
        "window_rates": [round(w, 3) for w in windows],
        "noise_pct": round(
            abs(windows[0] - windows[1]) / value * 100, 2
        ),
    }

    if sweep:
        # Print the flagship-only line BEFORE the (minutes-long) sweep of
        # the other buckets: a consumer that reads the LAST line gets the
        # full sweep result, while a harness that kills the process on a
        # timeout still finds a complete, valid flagship line.
        print(json.dumps(out), flush=True)
        buckets = sweep_buckets()
        per_bucket = {f"{BUCKET[0]}x{BUCKET[1]}": value}
        rates = {BUCKET: ips}
        # Effective per-bucket batch: an OOM retry drops a bucket to batch
        # 2, whose rate is NOT comparable (batch 1-2 halves MFU — see
        # BUCKETBENCH.json batch_scaling) — record it so a mixed-batch
        # weighted_mix is visible instead of silently understated.
        bucket_batch = {f"{BUCKET[0]}x{BUCKET[1]}": flag_batch}
        for hw, _share in buckets:
            if hw == BUCKET:
                continue
            b_eff, (b_ips, _b_mfu, _b_windows) = _run_with_oom_retry(
                batch_size, hw, SWEEP_MEASURE_STEPS
            )
            rates[hw] = b_ips
            per_bucket[f"{hw[0]}x{hw[1]}"] = round(b_ips, 3)
            bucket_batch[f"{hw[0]}x{hw[1]}"] = b_eff
        # Mix-weighted throughput: steps are drawn per bucket with the
        # COCO aspect shares, so the average COST per image is the
        # share-weighted mean of 1/rate (harmonic mix), not of the rates.
        total_share = sum(s for _, s in buckets)
        cost = sum(s / rates[hw] for hw, s in buckets) / total_share
        out["per_bucket"] = per_bucket
        out["weighted_mix"] = round(1.0 / cost, 3)
        out["mix_shares"] = {
            f"{hw[0]}x{hw[1]}": s for hw, s in buckets
        }
        if len(set(bucket_batch.values())) > 1:
            out["per_bucket_batch"] = bucket_batch
            out["weighted_mix_caveat"] = (
                "buckets measured at differing batch sizes (OOM retry); "
                "weighted_mix mixes non-comparable rates"
            )

    print(json.dumps(out))

    if os.environ.get("BENCH_CHECK", "") not in ("", "0"):
        raise SystemExit(check_against_committed(value))


if __name__ == "__main__":
    main()
