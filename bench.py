"""Benchmark: flagship train-step AND eval/detect throughput, ONE JSON line.

``--mode train`` (default) measures images/sec/chip for the full jitted
SPMD training step (forward, on-device target assignment, focal +
smooth-L1 losses, backward, optimizer update) on RetinaNet ResNet-50-FPN
at the reference's flagship resolution bucket (800x1344, BASELINE.json:10),
bf16 compute.

``--mode eval`` measures the eval fast path (ISSUE 2, BASELINE.json
configs[4] "on-device batched NMS"): per live bucket, the AOT-compiled
detect program (forward → sigmoid → decode → clip → batched NMS) in
ms/batch and imgs/s/chip, the POST-PROCESS alone (sigmoid+decode+clip+NMS
on synthetic head outputs — the tripwire for the 30-40x NMS/top-k rewrite
history, ops/nms.py), and an end-to-end sequential-vs-pipelined
``run_coco_eval`` comparison (the measured speedup of the overlapped
driver, plus a bit-identity check of its detections).  The committed
record is EVALBENCH.json; ``make evalbench-check`` is the regression
tripwire (same −3% band policy as bench-check).

TPU-tunnel outage hardening (VERDICT r5 missing #1 / weak #1): BOTH modes
first probe the default backend with a tiny matmul IN A SUBPROCESS (a dead
tunnel can HANG backend init, not just raise) with bounded retries and
backoff.  On persistent unavailability — or an UNAVAILABLE-class error
mid-run — the bench prints ONE structured JSON line
(``{"error": "tpu_unreachable", ...}`` including the committed
last-known-good rate, labeled as such) and exits with the distinct code
75 (EX_TEMPFAIL), never a bare rc-1 traceback like ``BENCH_r05.json``.
Real errors (OOM, shape bugs) still propagate loudly.

``--mode serve`` measures the dynamic-batching inference server (ISSUE 4,
serve/): per live bucket it AOT-builds the same detect executable the
server dispatches, measures the in-run sequential detect CEILING on it,
then drives the server with a saturating closed loop (2×batch client
threads, steady-state window after a warm period) and reports imgs/s,
``vs_ceiling`` (the acceptance bar: ≥0.9 on the chip), p50/p99 request
latency, and an overload leg — an open-loop flood against tiny bounded
queues that must SHED (reject-with-reason, every accepted request
resolves, bounded p99) rather than queue unboundedly.  The committed
record is SERVEBENCH.json; ``make servebench-check`` is the tripwire.
Knobs: SERVEBENCH_STEPS (window), SERVEBENCH_OVERLOAD=0 (skip the
overload leg), BENCH_SWEEP=0 (flagship bucket only).

``--mode comm`` measures the gradient-communication subsystem (ISSUE 13,
comm/) on a forced COMMBENCH_DEVICES-wide virtual CPU mesh: static
bytes-on-wire vs the exact schedule (the ROADMAP's ≤ 0.65× claim, from
the plan arithmetic — device-independent), step-time delta per variant
(int8 / int8+overlap / bf16 / 1 MB buckets; indicative only on the
virtual mesh), and loss/param parity drift after N identical steps vs
the exact run.  The hierarchical leg (ISSUE 16) routes the int8 policy
through the two-fabric tree on an emulated 2-slice topology and records
the per-hop split: DCN bytes ≤ 0.65× the all-exact hierarchical tree,
ZERO quantized ICI bytes, drift in the flat band.  The committed record
is COMMBENCH.json (written by ``scripts/commbench_sweep.py`` /
COMMBENCH_OUT); ``make commbench-check`` is the tripwire (bytes ratio
hard ≤ 0.65 AND ≤ committed + 0.02, the per-hop claims, parity-drift
band, device-class guard).

``vs_baseline``: the reference's own throughput was never recorded
(BASELINE.json "published": {}, see BASELINE.md), so the ratio is computed
against the first recorded bench of this rebuild (BENCH_r1.json) when
present, else 1.0 — i.e. it tracks round-over-round improvement.

Bucket sweep (round 4, VERDICT r3 missing #3): the multiscale pipeline
emits TWO static buckets at the flagship 800/1333 config
(data/pipeline.default_buckets: 800x1344 landscape+near-square, 1344x800
portrait; the former third 1088x1088 bucket was proven unreachable and
dropped in round 5 — see default_buckets' docstring) — the training
wall-clock model must not assume every step runs at the landscape-bucket
rate.  By default the bench sweeps both and reports ``per_bucket``
imgs/s/chip plus ``weighted_mix``, the COCO-aspect-share-weighted rate
(shares below).  ``value`` stays the flagship 800x1344 number so
round-over-round comparisons hold.  BENCH_SWEEP=0 restores the
single-bucket bench.

In sweep mode the flagship-only line prints FIRST and the full line
(same schema + sweep keys) LAST: any consumer that reads either the
first or the last JSON line gets a valid record, even if the process is
killed mid-sweep.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

BUCKET = (800, 1344)

# ---------------------------------------------------------------------------
# Outage machinery — STDLIB ONLY, defined BEFORE the heavy imports below.
# BENCH_r05.json proved classification must cover EVERY phase: with the
# driver's backend shim installed, `import optax`/`import jax` can itself
# run an eager op (lazy dispatch through convert_element_type) and die
# with "Unable to initialize backend ... UNAVAILABLE" before main() ever
# starts.  The classifier and the structured-line emitter therefore cannot
# live below those imports, and the imports themselves are guarded.
# ---------------------------------------------------------------------------

# Distinct exit code for "the accelerator is unreachable" (EX_TEMPFAIL):
# the driver's artifact can tell an environmental outage from a bench
# crash (rc 1) and from a measured regression (bench-check's exit 1).
EXIT_TPU_UNREACHABLE = 75

# The probe runs in a SUBPROCESS: a dead TPU tunnel can hang backend
# initialization indefinitely (observed: JAX_PLATFORMS=tpu init never
# returns on this box), and an in-process hang cannot be timed out.
_PROBE_SRC = (
    "import jax, jax.numpy as jnp; "
    "x = (jnp.ones((64, 64)) @ jnp.ones((64, 64))).sum(); "
    "print('probe_ok', float(x), jax.devices()[0].device_kind)"
)


def _probe_once(timeout_s: float) -> str | None:
    """One availability probe; returns None on success, else the error."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"probe timed out after {timeout_s:.0f}s (backend init hang)"
    if r.returncode == 0 and "probe_ok" in r.stdout:
        return None
    return (r.stderr.strip() or r.stdout.strip() or "probe failed")[-2000:]


def probe_device() -> tuple[int, str | None]:
    """Tiny-matmul availability probe with bounded retries and backoff.

    Returns (attempts_used, last_error); last_error None means reachable.
    Env knobs (the unit test shrinks them): BENCH_PROBE_ATTEMPTS (3),
    BENCH_PROBE_TIMEOUT_S (120), BENCH_PROBE_BACKOFF_S ("10,30" — seconds
    slept between attempts, last value reused if attempts exceed it).

    The retry loop this function grew is now utils/backoff.py's
    ``BackoffPolicy`` (ISSUE 12 satellite — the fleet router's health
    poller and re-dispatch path share the exact same schedule machinery);
    the import is jax-free (stdlib + the lazy utils package), so it's
    safe in this above-the-heavy-imports section.
    """
    from batchai_retinanet_horovod_coco_tpu.utils.backoff import (
        BackoffPolicy,
    )

    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    policy = BackoffPolicy.from_env_schedule(
        attempts, os.environ.get("BENCH_PROBE_BACKOFF_S", "10,30")
    )
    return policy.retry(lambda: _probe_once(timeout_s))


_UNAVAILABLE_MARKERS = (
    "unavailable",
    "unable to initialize backend",
    "deadline_exceeded",
    "failed to connect",
    "backend init hang",
)


def is_unavailable_error(err: "BaseException | str") -> bool:
    """Classify accelerator-unreachable errors (retryable outages).

    Deliberately narrow: RESOURCE_EXHAUSTED (OOM) and ordinary Python
    errors are REAL failures and must keep propagating as rc 1.  Generic
    socket noise ("connection reset", "socket closed") is deliberately
    NOT matched — the multiprocess input pipeline's worker crashes can
    surface as ConnectionResetError, and a real pipeline regression must
    not be laundered into an environmental outage.

    Exceptions are matched through their WHOLE ``__cause__``/``__context__``
    chain, not just the top frame: jax re-wraps backend-init failures
    (traceback filtering, deferred-dispatch shims), and the r05 crash
    class surfaces the UNAVAILABLE RuntimeError one link down from
    whatever the consumer finally raises.  If any link in the chain is a
    backend-init outage, the run is environmentally dead regardless of
    what wrapped it.
    """
    if isinstance(err, BaseException):
        seen: set[int] = set()
        stack: list = [err]
        while stack:
            e = stack.pop()
            if e is None or id(e) in seen:
                continue
            seen.add(id(e))
            text = str(e).lower()
            if any(m in text for m in _UNAVAILABLE_MARKERS):
                return True
            stack.extend((e.__cause__, e.__context__))
        return False
    text = str(err).lower()
    return any(m in text for m in _UNAVAILABLE_MARKERS)


def _artifact_path(name: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


def last_known_good(mode: str) -> dict | None:
    """The committed rate for ``mode``, clearly labeled as stale."""
    try:
        if mode == "eval":
            with open(_artifact_path("EVALBENCH.json")) as f:
                data = json.load(f)
            value, source = float(data["value"]), "EVALBENCH.json"
        elif mode == "serve":
            with open(_artifact_path("SERVEBENCH.json")) as f:
                data = json.load(f)
            value, source = float(data["value"]), "SERVEBENCH.json"
        elif mode == "comm":
            with open(_artifact_path("COMMBENCH.json")) as f:
                data = json.load(f)
            value, source = float(data["value"]), "COMMBENCH.json"
        else:
            with open(_artifact_path("BUCKETBENCH.json")) as f:
                data = json.load(f)
            value = float(
                data["per_bucket_imgs_per_sec_per_chip"][
                    f"{BUCKET[0]}x{BUCKET[1]}"
                ]
            )
            source = "BUCKETBENCH.json"
    except (OSError, KeyError, ValueError):
        return None
    return {
        "value": value,
        "source": source,
        "note": "committed last-known-good, NOT a fresh measurement",
    }


def emit_unreachable(
    mode: str, attempts: int, last_error: str, phase: str
) -> "SystemExit":
    """Print the ONE structured outage line; return SystemExit(75).

    The line is the whole contract: a consumer that parses either the
    first or the last stdout JSON line gets a classified record with the
    committed rate attached, instead of a 500-line traceback.
    ``phase`` is "import" | "probe" | "mid-run".
    """
    print(
        json.dumps(
            {
                "error": "tpu_unreachable",
                "mode": mode,
                "phase": phase,  # "probe" | "mid-run"
                "metric": {
                    "eval": "eval_images_per_sec_per_chip",
                    "serve": "serve_images_per_sec_per_chip",
                    "comm": "comm_bytes_on_wire_ratio",
                }.get(mode, "train_images_per_sec_per_chip"),
                "attempts": attempts,
                "last_error": str(last_error)[-2000:],
                "last_known_good": last_known_good(mode),
                "exit_code": EXIT_TPU_UNREACHABLE,
            }
        ),
        flush=True,
    )
    return SystemExit(EXIT_TPU_UNREACHABLE)


def _mode_from_argv() -> str:
    """Best-effort --mode for an import-phase outage record (argparse has
    not run yet when a heavy import dies)."""
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--mode" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--mode="):
            return a.split("=", 1)[1]
    return "train"


# Heavy imports, GUARDED: with a backend shim installed (the driver's
# environment), merely importing these can run an eager op and raise the
# backend-init UNAVAILABLE RuntimeError — the exact BENCH_r05 crash class.
# That is an outage in the "import" phase, not a bench bug; classify it
# when bench.py is the program (an importing test must keep the raw error).
try:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from batchai_retinanet_horovod_coco_tpu.obs import trace as obs_trace
except Exception as _import_error:  # pragma: no cover — subprocess-tested
    if __name__ == "__main__" and is_unavailable_error(_import_error):
        raise emit_unreachable(
            _mode_from_argv(), 1, str(_import_error), phase="import"
        ) from None
    raise


WARMUP_STEPS = 5
# 60 steps ≈ 7.5 s of device time: the tunnel's per-step dispatch jitter
# showed up as ±1 imgs/s run-to-run at 20 steps (round 3); tripling the
# window cuts that to ~±0.3 while keeping the whole bench under a minute.
MEASURE_STEPS = 60

# Approximate share of COCO train2017 images landing in each bucket the
# flagship-config pipeline emits, keyed by the bucket's ASPECT CLASS so
# a reorder of default_buckets cannot silently swap shares: landscape
# AND square images land in 800x1344 (every resized landscape/square
# fits it), portraits (any severity) in 1344x800 — the exhaustive
# routing scan in tests/unit/test_buckets.py pins this keying against
# data/pipeline.bucket_for_source.  Shares are ESTIMATES from the
# public COCO size distribution (~640x480-class landscape dominates;
# portraits ~25%); re-derive exactly with `debug.py buckets` on the
# real annotations.
_MIX_SHARES = {"landscape": 0.77, "portrait": 0.23}


def sweep_buckets() -> tuple[tuple[tuple[int, int], float], ...]:
    """(bucket, share) pairs — shapes from the pipeline's single source
    of truth (default_buckets), so the sweep cannot silently drift from
    the shapes a training run actually compiles; only the COCO share
    estimates live here, keyed by aspect class."""
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        default_buckets,
    )

    buckets = default_buckets(800, 1333)
    # Runtime schema checks, not debug asserts: under `python -O` a bare
    # assert would vanish and a reordered default_buckets could silently
    # pair shares with the wrong shapes.
    if buckets[0] != BUCKET:
        raise RuntimeError(
            f"default_buckets(800, 1333) now leads with {buckets[0]}, not "
            f"{BUCKET} — update BUCKET (the round-over-round headline "
            "shape) and _MIX_SHARES together"
        )
    if len(buckets) == 1:
        return ((buckets[0], 1.0),)

    def aspect_class(hw: tuple[int, int]) -> str:
        h, w = hw
        return "landscape" if h < w else ("portrait" if h > w else "square")

    classes = [aspect_class(b) for b in buckets]
    if sorted(classes) != sorted(_MIX_SHARES):
        raise RuntimeError(
            f"default_buckets aspect classes {classes} no longer match the "
            f"share table {sorted(_MIX_SHARES)} — update _MIX_SHARES"
        )
    return tuple((b, _MIX_SHARES[c]) for b, c in zip(buckets, classes))


# Fewer timed steps for the non-flagship buckets: they only feed the
# weighted mix, and the sweep must stay under the driver's bench budget.
SWEEP_MEASURE_STEPS = 30

def _device_peak_tflops() -> float | None:
    """Spec-sheet peak only (the bench's MFU is a chip number; the perf
    doctor separately applies its labeled nominal-CPU figure).  The table
    itself lives in obs/analyze — ONE source of truth with the per-run
    report."""
    from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
        device_peak_tflops,
    )

    peak, source = device_peak_tflops(jax.devices()[0].device_kind)
    return peak if source == "spec" else None


def _trace_attribution() -> dict | None:
    """The analyzer's span attribution over this process's live rings
    (--trace runs only): folded into the committed JSON line so the
    BENCH_rNN trajectory carries data_wait%/overlap% history alongside
    imgs/s and schedule provenance."""
    if not obs_trace.enabled():
        return None
    try:
        from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
            span_attribution,
        )

        return span_attribution(obs_trace.snapshot_events())
    except Exception as e:  # attribution must never fail the bench
        print(f"# trace attribution failed: {e!r}", flush=True)
        return None


def make_batch(batch_size: int, hw: tuple[int, int], max_gt: int = 100):
    rng = np.random.default_rng(0)
    h, w = hw
    gt_boxes = np.zeros((batch_size, max_gt, 4), np.float32)
    gt_labels = np.zeros((batch_size, max_gt), np.int32)
    gt_mask = np.zeros((batch_size, max_gt), bool)
    for b in range(batch_size):
        n = int(rng.integers(4, 24))
        xy = rng.uniform(0, [w - 64, h - 64], (n, 2))
        wh = rng.uniform(16, 256, (n, 2))
        gt_boxes[b, :n, 0::2] = np.stack([xy[:, 0], np.minimum(xy[:, 0] + wh[:, 0], w)], 1)
        gt_boxes[b, :n, 1::2] = np.stack([xy[:, 1], np.minimum(xy[:, 1] + wh[:, 1], h)], 1)
        gt_labels[b, :n] = rng.integers(0, 80, n)
        gt_mask[b, :n] = True
    return {
        # uint8, as the pipeline ships it (normalization runs on device and
        # fuses into the stem; measured ~2% faster than feeding f32).
        "images": jnp.asarray(
            rng.integers(0, 256, (batch_size, h, w, 3), dtype=np.uint8)
        ),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


def run_bench(
    batch_size: int,
    hw: tuple[int, int] = BUCKET,
    measure_steps: int = MEASURE_STEPS,
    numerics: bool = False,
) -> tuple[float, float | None]:
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.obs.numerics import (
        NumericsConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.train import (
        create_train_state,
        make_train_step,
    )

    # frozen_bn is the reference's fine-tune configuration (BN frozen during
    # detection training, SURVEY.md M2) and measures ~9% faster than GN on
    # v5e (pure scale+bias fuses into the convs; GN's per-group moments are
    # extra bandwidth-bound passes).
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", norm_kind="frozen_bn"
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01, momentum=0.9), (1, *hw, 3), jax.random.key(0)
    )
    # numerics=True measures the ISSUE-10 in-step summary's overhead
    # (the committed JSON line's numerics_overhead field states the
    # on-vs-off delta); the default step is byte-identical to pre-ISSUE-10.
    step = make_train_step(
        model, hw, 80, donate_state=True,
        numerics=NumericsConfig(enabled=numerics),
    )
    batch = make_batch(batch_size, hw)

    # AOT-compile once: the executable both runs the loop and reports the
    # XLA-counted FLOPs of the whole step (forward, assignment, losses,
    # backward, update) for the MFU number.
    with obs_trace.span("aot_compile_train", bucket=f"{hw[0]}x{hw[1]}"):
        compiled = step.lower(state, batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else None
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    for _ in range(min(WARMUP_STEPS, measure_steps)):
        state, metrics = compiled(state, batch)
    # Same hard sync as the timed region: block_until_ready can return
    # early on tunneled backends, which would leak warmup work into t0.
    float(metrics["loss"])

    # TWO disjoint timed windows (VERDICT r4 weak #1): the point estimate
    # alone cannot distinguish tunnel noise from a real regression; the
    # window-to-window spread is a same-run noise floor reported beside
    # the value.  Each window hard-syncs INSIDE its timed region: on
    # tunneled backends, block_until_ready on jit-call results can
    # return before the device finishes (measured 2 ms/step "throughput"
    # on a 376 ms step); pulling a scalar to host cannot lie.
    half = max(1, measure_steps // 2)
    window_rates = []
    dt_total = 0.0
    for _ in range(2):
        with obs_trace.span("train_window", bucket=f"{hw[0]}x{hw[1]}"):
            t0 = time.perf_counter()
            for _ in range(half):
                state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
        assert np.isfinite(loss)
        window_rates.append(batch_size * half / dt)
        dt_total += dt

    ips = batch_size * 2 * half / dt_total
    peak = _device_peak_tflops()
    mfu = None
    if step_flops > 0 and peak:
        achieved_tflops = step_flops * (2 * half / dt_total) / 1e12
        mfu = achieved_tflops / peak
    return ips, mfu, tuple(window_rates)


def first_recorded_bench() -> float | None:
    vals = {}
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
            # The driver wraps the printed line under "parsed".
            if "value" not in data and "parsed" in data:
                data = data["parsed"]
            vals[int(m.group(1))] = float(data["value"])
        except Exception:
            continue
    return vals[min(vals)] if vals else None


def _run_with_oom_retry(batch_size, hw, measure_steps):
    try:
        return batch_size, run_bench(batch_size, hw, measure_steps)
    except Exception as e:
        # Retry smaller only for HBM exhaustion; real bugs propagate.
        oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
        if batch_size <= 2 or not oom:
            raise
        print(f"# batch {batch_size} OOM at {hw}; retrying at 2", flush=True)
        return 2, run_bench(2, hw, measure_steps)


# Regression tripwire (VERDICT r4 weak #1): `make bench-check` fails when
# the fresh flagship rate lands below the committed BUCKETBENCH.json
# number minus this band.  3% ≈ the measured tunnel noise envelope (±1
# imgs/s run-to-run at the round-3 window size, r4's −0.5% drift): the
# r4-sized drift is classified noise BY THE TOOL, a real −5% fails loudly.
NOISE_BAND_PCT = 3.0


def _check_floor(
    label: str,
    value: float,
    committed_value: float,
    committed_device: str | None,
    device_kind: str | None,
) -> int:
    """The ONE floor checker both modes share: committed value − the noise
    band is the floor; exit 0 ok / 1 regression.

    Rates are only comparable within a device class, so when both device
    kinds are known and differ, the check reports loudly and passes — the
    fix is to re-capture the artifact on the right device, not to fail
    every run.  A legacy artifact without a recorded device (BUCKETBENCH
    predates the field) is a chip capture by provenance: it is only
    refused when THIS run is on the CPU fallback, where a "REGRESSION"
    verdict would misclassify an environmental condition as a perf bug.
    """
    if device_kind is not None:
        committed_desc = committed_device or "an unrecorded accelerator"
        mismatch = (
            committed_device != device_kind
            if committed_device is not None
            else device_kind == "cpu"
        )
        if mismatch:
            print(
                f"# {label}: committed artifact was captured on "
                f"{committed_desc} but this run is on {device_kind!r}; "
                "rates are not comparable across device classes — "
                "re-capture the artifact on this device"
            )
            return 0
    floor = committed_value * (1 - NOISE_BAND_PCT / 100)
    verdict = "ok" if value >= floor else "REGRESSION"
    print(
        f"# {label}: {value:.2f} imgs/s vs committed {committed_value:.2f} "
        f"(floor {floor:.2f} = -{NOISE_BAND_PCT}%): {verdict}"
    )
    return 0 if value >= floor else 1


def check_against_committed(value: float, device_kind: str | None = None) -> int:
    """Compare a fresh flagship TRAIN rate against the committed baseline;
    returns a process exit code (0 ok / 1 regression).  ``device_kind``
    (when given) guards against comparing across device classes."""
    path = os.path.join(os.path.dirname(__file__) or ".", "BUCKETBENCH.json")
    try:
        with open(path) as f:
            data = json.load(f)
        committed = float(
            data["per_bucket_imgs_per_sec_per_chip"][
                f"{BUCKET[0]}x{BUCKET[1]}"
            ]
        )
    except (OSError, KeyError, ValueError) as e:
        print(f"# bench-check: cannot read committed baseline: {e}")
        return 1
    return _check_floor(
        "bench-check", value, committed, data.get("device_kind"), device_kind
    )


# --- eval mode (ISSUE 2: the detect/NMS fast path) -----------------------

EVAL_WARMUP_STEPS = 3


def _eval_model_and_state(num_classes: int = 80):
    """The flagship inference model (shared across buckets; fully conv, so
    the init shape is small and the params serve every bucket)."""
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=num_classes, backbone="resnet50",
            norm_kind="frozen_bn",
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01, momentum=0.9), (1, 256, 256, 3),
        jax.random.key(0),
    )
    return model, state


def _sync_scalar(det) -> None:
    """Hard host sync: pull a detection scalar (block_until_ready can
    return early on tunneled backends; a host transfer cannot lie)."""
    float(np.asarray(jax.device_get(det.scores))[0, 0])


def run_postprocess_bucket(
    batch_size: int, hw: tuple[int, int], measure_steps: int
) -> float:
    """ms/batch of the POST-PROCESS alone: sigmoid → decode → clip →
    batched NMS on synthetic head outputs at this bucket's anchor count.

    This is the isolation tripwire for ops/nms.py's fixed-point NMS and
    two-stage top-k (both carry measured 30-40x rewrite histories): a
    regression there moves this number even when the conv-bound full
    detect program hides it.
    """
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        nms_fn_for,
        resolve_detect_config,
    )
    from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
    from batchai_retinanet_horovod_coco_tpu.ops import boxes as boxes_lib

    # Schedule-resolved (tune/): the tripwire measures the committed
    # winner (impl + block + pre_nms_size), not a hardcoded config.
    cfg = resolve_detect_config(DetectConfig())
    anchors = anchors_lib.anchors_for_image_shape(hw, cfg.anchor)
    rng = np.random.default_rng(1)
    # sigmoid(-4 ± 1) ≈ 2% mean foreground probability: a realistic sparse
    # score field, so the score-threshold mask and top-k see typical work.
    cls = jnp.asarray(
        rng.normal(-4.0, 1.0, (batch_size, anchors.shape[0], 80)).astype(
            np.float32
        )
    )
    deltas = jnp.asarray(
        rng.normal(0.0, 0.3, (batch_size, anchors.shape[0], 4)).astype(
            np.float32
        )
    )
    anchors_dev = jnp.asarray(anchors)
    nms = nms_fn_for(cfg)

    def post(cls_logits, box_deltas):
        scores = jax.nn.sigmoid(cls_logits)
        boxes = boxes_lib.decode_boxes(anchors_dev[None], box_deltas, cfg.codec)
        boxes = boxes_lib.clip_boxes(boxes, hw)
        return nms(boxes, scores)

    compiled = jax.jit(post).lower(cls, deltas).compile()
    det = None
    for _ in range(2):
        det = compiled(cls, deltas)
    _sync_scalar(det)
    steps = max(1, measure_steps // 2)
    t0 = time.perf_counter()
    for _ in range(steps):
        det = compiled(cls, deltas)
    _sync_scalar(det)
    return round((time.perf_counter() - t0) / steps * 1e3, 2)


def run_eval_bucket(
    model, state, batch_size: int, hw: tuple[int, int], measure_steps: int
) -> dict:
    """One bucket's eval-path numbers: the AOT-compiled detect program
    (forward → decode → NMS) in two disjoint timed windows (same noise
    policy as the train bench) plus the postprocess-only figure."""
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        compile_detect_fn,
    )

    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.integers(0, 256, (batch_size, *hw, 3), dtype=np.uint8)
    )
    # AOT compile via the ONE shared bench/serve path (the span naming the
    # compile lives inside compile_detect_fn).
    call = compile_detect_fn(model, state, hw, batch_size, DetectConfig())
    det = None
    for _ in range(EVAL_WARMUP_STEPS):
        det = call(images)
    _sync_scalar(det)

    half = max(1, measure_steps // 2)
    window_rates = []
    dt_total = 0.0
    for _ in range(2):
        with obs_trace.span("eval_window", bucket=f"{hw[0]}x{hw[1]}"):
            t0 = time.perf_counter()
            for _ in range(half):
                det = call(images)
            _sync_scalar(det)
            dt = time.perf_counter() - t0
        window_rates.append(batch_size * half / dt)
        dt_total += dt
    ips = batch_size * 2 * half / dt_total
    return {
        "imgs_per_sec": round(ips, 3),
        "detect_ms_per_batch": round(dt_total / (2 * half) * 1e3, 2),
        "postprocess_ms_per_batch": run_postprocess_bucket(
            batch_size, hw, measure_steps
        ),
        "window_rates": [round(w, 3) for w in window_rates],
        "noise_pct": round(
            abs(window_rates[0] - window_rates[1]) / max(ips, 1e-9) * 100, 2
        ),
        "batch": batch_size,
    }


def run_e2e_compare() -> dict:
    """Measured end-to-end ``run_coco_eval`` wall-clock, sequential vs
    pipelined, on a synthetic COCO split — the committed evidence that the
    three-stage overlap pays, plus an in-run bit-identity check of the two
    paths' detections.  Both passes share ONE compiled detect program
    (``detect_fns``), so the comparison times the drivers, not compiles.

    The head is sized to the synthetic palette (8 classes — every detect
    label must map through the dataset's ``label_to_cat_id``); the
    backbone/FPN cost, which dominates the device side, matches flagship.
    """
    import tempfile

    num_images = int(os.environ.get("EVALBENCH_E2E_IMAGES", "32"))
    size = int(os.environ.get("EVALBENCH_E2E_SIZE", "320"))
    batch = int(os.environ.get("EVALBENCH_E2E_BATCH", "4"))
    model, state = _eval_model_and_state(num_classes=8)
    tmp = tempfile.TemporaryDirectory(prefix="evalbench_")
    try:
        return _run_e2e_compare(tmp.name, model, state, num_images, size, batch)
    finally:
        tmp.cleanup()


def _run_e2e_compare(root, model, state, num_images, size, batch) -> dict:
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
        make_synthetic_coco,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        collect_detections,
        make_detect_fn,
        run_coco_eval,
    )

    make_synthetic_coco(
        root, num_images=num_images, num_classes=8,
        image_size=(size, size), seed=0,
    )
    ds = CocoDataset(
        os.path.join(root, "instances_train.json"),
        os.path.join(root, "train"),
    )
    pipe_cfg = PipelineConfig(
        batch_size=batch, buckets=((size, size),), min_side=size,
        max_side=size, max_gt=100, shuffle=False, hflip_prob=0.0,
        drop_remainder=False, num_workers=2,
    )
    # The untrained head's π=0.01 score prior sits below the production
    # 0.05 threshold, which would make both passes emit ZERO detections —
    # a vacuous bit-identity check and no host-side conversion/scoring
    # load at all.  A 0.001 threshold floods the consumer at
    # max_detections volume instead (an upper bound on trained-model host
    # load — the honest direction for a pipeline bench).
    cfg = DetectConfig(score_threshold=0.001)
    hw = (size, size)
    detect_fns = {hw: make_detect_fn(model, hw, cfg)}
    # Compile once OUTSIDE both timed passes.
    jax.device_get(
        detect_fns[hw](state, jnp.zeros((batch, size, size, 3), jnp.uint8))
    )

    def eval_pass(pipelined: bool) -> tuple[float, dict]:
        batches = build_pipeline(ds, pipe_cfg, train=False)
        try:
            with obs_trace.span("e2e_eval", pipelined=pipelined):
                t0 = time.perf_counter()
                metrics = run_coco_eval(
                    state, model, ds, batches, cfg,
                    pipelined=pipelined, detect_fns=detect_fns,
                )
                dt = time.perf_counter() - t0
            return dt, metrics
        finally:
            batches.close()

    def detect_pass(pipelined: bool) -> list[dict]:
        batches = build_pipeline(ds, pipe_cfg, train=False)
        try:
            return collect_detections(
                state, model, ds, batches, cfg,
                pipelined=pipelined, detect_fns=detect_fns,
            )
        finally:
            batches.close()

    t_seq, m_seq = eval_pass(False)
    t_pipe, m_pipe = eval_pass(True)
    dt_seq = detect_pass(False)
    bit_identical = dt_seq == detect_pass(True)
    return {
        "images": num_images,
        "bucket": f"{size}x{size}",
        "batch": batch,
        "score_threshold": cfg.score_threshold,
        "detections": len(dt_seq),
        "sequential_s": round(t_seq, 3),
        "pipelined_s": round(t_pipe, 3),
        "speedup": round(t_seq / max(t_pipe, 1e-9), 3),
        "bit_identical": bool(bit_identical),
        "map_equal": bool(m_seq == m_pipe),
    }


def check_eval_against_committed(value: float, device_kind: str) -> int:
    """evalbench-check: fresh flagship EVAL rate vs the committed
    EVALBENCH.json — same floor/device policy as bench-check
    (``_check_floor``)."""
    try:
        with open(_artifact_path("EVALBENCH.json")) as f:
            committed = json.load(f)
        committed_value = float(committed["value"])
    except (OSError, KeyError, ValueError) as e:
        print(f"# evalbench-check: cannot read committed baseline: {e}")
        return 1
    return _check_floor(
        "evalbench-check",
        value,
        committed_value,
        str(committed.get("device_kind", "")) or None,
        device_kind,
    )


def run_eval_mode() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    measure_steps = int(os.environ.get("EVALBENCH_STEPS", str(MEASURE_STEPS)))
    # The check targets need only the flagship scalar: BENCH_SWEEP=0 skips
    # the non-flagship buckets (same knob as train mode) and
    # EVALBENCH_E2E=0 skips the minutes-long sequential-vs-pipelined
    # comparison, so `make bench-check`/`evalbench-check` stay cheap.
    sweep = os.environ.get("BENCH_SWEEP", "1") not in ("", "0")
    with_e2e = os.environ.get("EVALBENCH_E2E", "1") not in ("", "0")
    model, state = _eval_model_and_state()
    device_kind = jax.devices()[0].device_kind

    # Per-bucket eval batch from the device's schedule when tuned
    # (tune/schedule.py); BENCH_BATCH (or the default 8) for untuned
    # buckets.  An explicit BENCH_BATCH env pins every bucket.
    from batchai_retinanet_horovod_coco_tpu.tune import eval_batch_for

    pinned = "BENCH_BATCH" in os.environ

    per_bucket: dict[str, dict] = {}
    value = None
    for hw, _share in sweep_buckets():
        if not sweep and hw != BUCKET:
            continue
        bucket_batch = (
            batch_size if pinned else eval_batch_for(hw, batch_size)
        )
        try:
            r = run_eval_bucket(model, state, bucket_batch, hw, measure_steps)
        except Exception as e:
            oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
            if bucket_batch <= 2 or not oom:
                raise
            print(f"# batch {bucket_batch} OOM at {hw}; retrying at 2", flush=True)
            r = run_eval_bucket(model, state, 2, hw, measure_steps)
        per_bucket[f"{hw[0]}x{hw[1]}"] = r
        if hw == BUCKET:
            value = r["imgs_per_sec"]

    out = {
        "metric": "eval_images_per_sec_per_chip",
        "mode": "eval",
        "value": value,
        "unit": "images/sec/chip",
        "device_kind": device_kind,
        "measure_steps": measure_steps,
        "per_bucket": per_bucket,
        # Print a valid flagship record BEFORE the minutes-long e2e
        # comparison (same kill-safety contract as the train sweep).
    }
    att = _trace_attribution()
    if att is not None:
        out["attribution"] = att
    print(json.dumps(out), flush=True)
    if with_e2e:
        out["e2e"] = run_e2e_compare()
        # Re-derive: the e2e pass added the pipelined dispatch/fetch
        # spans the overlap ratio reads.
        att = _trace_attribution()
        if att is not None:
            out["attribution"] = att
        print(json.dumps(out))

    if os.environ.get("BENCH_CHECK", "") not in ("", "0"):
        raise SystemExit(check_eval_against_committed(value, device_kind))


# --- comm mode (ISSUE 13: the gradient-communication subsystem) -----------

# CPU-sized defaults: the comm bench runs on a FORCED virtual CPU mesh
# (COMMBENCH_DEVICES wide) — the measurands that matter are mesh-size
# arithmetic (bytes-on-wire ratio, static) and parity drift (numeric),
# which are device-independent; the step-time delta is recorded as
# indicative only (virtual-mesh collectives share one CPU).
COMM_DEVICES = 8
COMM_MEASURE_STEPS = 6
COMM_PARITY_STEPS = 10


def _comm_model_and_state():
    """Flagship topology at the dryrun's reduced width (the sharding and
    bucketing structure match the full model; CPU-compilable)."""
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", dtype=jnp.float32,
            fpn_channels=64, head_width=64,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, 64, 64, 3),
        jax.random.key(0),
    )
    return model, state


def _comm_batch(n: int, hw=(64, 64)):
    rng = np.random.default_rng(0)
    b = n
    return {
        "images": jnp.asarray(
            rng.normal(0, 1, (b, *hw, 3)).astype(np.float32)
        ),
        "gt_boxes": jnp.asarray(
            np.tile(
                np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (b, 1, 1)
            )
        ),
        "gt_labels": jnp.zeros((b, 1), np.int32),
        "gt_mask": jnp.ones((b, 1), bool),
    }


def _comm_timed_steps(step_fn, state, batch, steps: int) -> float:
    """Mean wall seconds/step with a hard scalar sync per step."""
    st = state
    st, m = step_fn(st, batch)
    float(m["loss"])  # warmup + sync
    t0 = time.perf_counter()
    for _ in range(steps):
        st, m = step_fn(st, batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / max(1, steps)


def _comm_run_variant(
    model, state, mesh, n, batch, comm_cfg, steps, topology=None
):
    """(timed s/step, final state after COMM_PARITY_STEPS, losses)."""
    from batchai_retinanet_horovod_coco_tpu.comm import init_comm_state
    from batchai_retinanet_horovod_coco_tpu.train import make_train_step

    st = state
    if comm_cfg is not None and comm_cfg.needs_state:
        st = st.replace(
            comm_state=jax.device_put(
                init_comm_state(
                    state.params, comm_cfg, n, topology=topology
                )
            )
        )
    step_fn = make_train_step(
        model, (64, 64), 80, mesh=mesh, comm=comm_cfg, topology=topology,
        donate_state=False,
    )
    s_per_step = _comm_timed_steps(step_fn, st, batch, steps)
    losses = []
    for _ in range(COMM_PARITY_STEPS):
        st, m = step_fn(st, batch)
        losses.append(float(m["loss"]))
    return s_per_step, st, losses


def _param_rel_drift(a, b) -> float:
    num = 0.0
    den = 0.0
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        d = np.asarray(la, np.float64) - np.asarray(lb, np.float64)
        num += float(np.sum(d * d))
        den += float(np.sum(np.asarray(lb, np.float64) ** 2))
    return float(np.sqrt(num / max(den, 1e-30)))


def run_comm_record(sweep: bool) -> dict:
    """Measure the comm subsystem on a forced virtual CPU mesh: static
    bytes-on-wire vs exact, step-time delta, and parity drift after
    COMM_PARITY_STEPS identical steps (exact vs compressed)."""
    from __graft_entry__ import _force_virtual_cpu_mesh

    n = int(os.environ.get("COMMBENCH_DEVICES", str(COMM_DEVICES)))
    steps = int(os.environ.get("COMMBENCH_STEPS", str(COMM_MEASURE_STEPS)))
    _force_virtual_cpu_mesh(n)
    from batchai_retinanet_horovod_coco_tpu.comm import (
        CommConfig,
        plan_buckets,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh

    model, state = _comm_model_and_state()
    mesh = make_mesh(n)
    batch = _comm_batch(n)

    exact_s, exact_state, exact_losses = _comm_run_variant(
        model, state, mesh, n, batch, None, steps
    )

    variants = [("int8", CommConfig(compress="int8"))]
    if sweep:
        variants += [
            ("int8_overlap", CommConfig(compress="int8", overlap=True)),
            ("bf16", CommConfig(compress="bf16")),
            ("int8_bucket1mb", CommConfig(compress="int8", bucket_mb=1.0)),
        ]
    per_variant: dict[str, dict] = {}
    for name, cfg in variants:
        plan = plan_buckets(state.params, cfg)
        v_s, v_state, v_losses = _comm_run_variant(
            model, state, mesh, n, batch, cfg, steps
        )
        per_variant[name] = {
            "compressed_bytes": plan.compressed_bytes(n),
            "exact_bytes": plan.exact_bytes(n),
            "bytes_ratio": round(
                plan.compressed_bytes(n) / max(1, plan.exact_bytes(n)), 4
            ),
            "s_per_step": round(v_s, 4),
            "step_time_delta_pct": round(
                (v_s - exact_s) / max(exact_s, 1e-9) * 100, 2
            ),
            "loss_drift_at_n": round(
                abs(v_losses[-1] - exact_losses[-1])
                / max(abs(exact_losses[-1]), 1e-9),
                6,
            ),
            "param_rel_drift_at_n": round(
                _param_rel_drift(v_state.params, exact_state.params), 6
            ),
            "buckets": len(plan.buckets),
        }

    # Hierarchical leg (ISSUE 16): the same int8 policy routed through
    # the two-fabric tree on an EMULATED 2-slice topology (the virtual
    # CPU mesh playing S slices x L devices) — exact f32 within each
    # slice, quantization only on the cross-slice DCN hop.  Always
    # measured (not sweep-gated): commbench-check enforces the per-hop
    # claims.  Needs an even mesh; a deliberately odd COMMBENCH_DEVICES
    # records the skip instead of faking a topology.
    if n % 2 == 0 and n >= 4:
        from batchai_retinanet_horovod_coco_tpu.parallel import CommTopology

        topo = CommTopology(num_slices=2, slice_size=n // 2)
        hier_cfg = CommConfig(compress="int8")  # ici exact, dcn int8
        assert hier_cfg.hierarchical_with(topo)
        hier_mesh = make_mesh(n, topology=topo)
        hplan = plan_buckets(state.params, hier_cfg, topo)
        h_s, h_state, h_losses = _comm_run_variant(
            model, state, hier_mesh, n, batch, hier_cfg, steps,
            topology=topo,
        )
        hop = hplan.hop_bytes(topo)
        hop_exact = hplan.hop_bytes_exact(topo)
        hop_quant = hplan.hop_quant_bytes(topo)
        per_variant["hier_int8_dcn"] = {
            "topology": f"{topo.num_slices}x{topo.slice_size}",
            "hop_bytes": hop,
            "hop_bytes_exact": hop_exact,
            "hop_quant_bytes": hop_quant,
            # Headline per-hop claims: the DCN hop's bytes vs the
            # all-exact hierarchical tree, and zero quantized ICI bytes.
            "dcn_bytes_ratio": round(
                hop["dcn"] / max(1, hop_exact["dcn"]), 4
            ),
            "ici_quant_bytes": hop_quant["ici"],
            "s_per_step": round(h_s, 4),
            "step_time_delta_pct": round(
                (h_s - exact_s) / max(exact_s, 1e-9) * 100, 2
            ),
            "loss_drift_at_n": round(
                abs(h_losses[-1] - exact_losses[-1])
                / max(abs(exact_losses[-1]), 1e-9),
                6,
            ),
            "param_rel_drift_at_n": round(
                _param_rel_drift(h_state.params, exact_state.params), 6
            ),
            "buckets": len(hplan.buckets),
        }
    flag = per_variant["int8"]
    return {
        "bench": "commbench",
        "metric": "comm_bytes_on_wire_ratio",
        "mode": "comm",
        # Headline: the int8 plan's compressed/exact bytes ratio (lower
        # is better; the ROADMAP claim is <= 0.65).
        "value": flag["bytes_ratio"],
        "unit": "compressed/exact bytes (per-device ring estimate)",
        "device_kind": jax.devices()[0].device_kind,
        "devices": n,
        "measure_steps": steps,
        "parity_steps": COMM_PARITY_STEPS,
        "exact_s_per_step": round(exact_s, 4),
        "per_variant": per_variant,
        "note": (
            "virtual-CPU-mesh capture: bytes/parity are device-"
            "independent; s_per_step is indicative only (collectives "
            "share one CPU)"
        ),
    }


def check_comm_against_committed(record: dict) -> int:
    """commbench-check: bytes ratio must hold the <= 0.65 claim AND not
    regress vs the committed COMMBENCH.json (+0.02 absolute tolerance);
    parity drift must stay within 3x the committed drift (floor 2e-2) —
    quantization noise is seed-stable but not bit-stable across jax
    versions.  Same device-class guard policy as the other modes."""
    try:
        with open(_artifact_path("COMMBENCH.json")) as f:
            committed = json.load(f)
    except (OSError, ValueError) as e:
        print(f"# commbench-check: cannot read committed baseline: {e}")
        return 1
    rc = 0
    fresh = record["per_variant"]["int8"]
    if committed.get("device_kind") != record["device_kind"]:
        print(
            f"# commbench-check: committed artifact is for "
            f"{committed.get('device_kind')!r}, this run is "
            f"{record['device_kind']!r} — rates not comparable across "
            "device classes; re-capture (bytes/parity checks still run)"
        )
    ratio = float(fresh["bytes_ratio"])
    if ratio > 0.65:
        print(
            f"# commbench-check: bytes ratio {ratio} > 0.65 — the "
            "compression claim no longer holds: REGRESSION"
        )
        rc = 1
    committed_ratio = float(
        committed.get("per_variant", {}).get("int8", {}).get(
            "bytes_ratio", committed.get("value", 0.65)
        )
    )
    if ratio > committed_ratio + 0.02:
        print(
            f"# commbench-check: bytes ratio regressed "
            f"{committed_ratio} -> {ratio} (> +0.02): REGRESSION"
        )
        rc = 1
    committed_drift = float(
        committed.get("per_variant", {}).get("int8", {}).get(
            "param_rel_drift_at_n", 0.0
        )
    )
    drift = float(fresh["param_rel_drift_at_n"])
    ceiling = max(3 * committed_drift, 2e-2)
    if drift > ceiling:
        print(
            f"# commbench-check: parity drift {drift} > {ceiling} "
            f"(3x committed {committed_drift}, floor 2e-2): REGRESSION"
        )
        rc = 1
    # Hierarchical leg (ISSUE 16): the per-hop claims — the DCN hop's
    # compressed bytes hold <= 0.65x the all-exact hierarchical tree,
    # the ICI hops carry ZERO quantized bytes, and the parity drift vs
    # the exact flat tree stays in the same band as the flat variant.
    hier = record["per_variant"].get("hier_int8_dcn")
    if hier is None:
        print(
            "# commbench-check: no hierarchical leg in this run "
            "(odd COMMBENCH_DEVICES?) — per-hop claims unchecked: "
            "REGRESSION"
        )
        rc = 1
    else:
        dcn_ratio = float(hier["dcn_bytes_ratio"])
        if dcn_ratio > 0.65:
            print(
                f"# commbench-check: DCN bytes ratio {dcn_ratio} > 0.65 "
                "— the per-hop compression claim no longer holds: "
                "REGRESSION"
            )
            rc = 1
        if int(hier["ici_quant_bytes"]) != 0:
            print(
                f"# commbench-check: ICI hops carry "
                f"{hier['ici_quant_bytes']} quantized bytes (must be 0 "
                "— the fast wire stays exact): REGRESSION"
            )
            rc = 1
        committed_hier_drift = float(
            committed.get("per_variant", {}).get("hier_int8_dcn", {}).get(
                "param_rel_drift_at_n", 0.0
            )
        )
        hier_drift = float(hier["param_rel_drift_at_n"])
        hier_ceiling = max(3 * committed_hier_drift, 2e-2)
        if hier_drift > hier_ceiling:
            print(
                f"# commbench-check: hierarchical parity drift "
                f"{hier_drift} > {hier_ceiling} (3x committed "
                f"{committed_hier_drift}, floor 2e-2): REGRESSION"
            )
            rc = 1
    if rc == 0:
        print(
            f"# commbench-check: bytes ratio {ratio} <= 0.65 (committed "
            f"{committed_ratio}), parity drift {drift} <= {ceiling}, "
            f"DCN ratio {hier['dcn_bytes_ratio']} <= 0.65 with 0 "
            "quantized ICI bytes: ok"
        )
    return rc


def run_comm_mode() -> None:
    sweep = os.environ.get("BENCH_SWEEP", "1") not in ("", "0")
    record = run_comm_record(sweep)
    print(json.dumps(record), flush=True)
    out_path = os.environ.get("COMMBENCH_OUT")
    if out_path:
        from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
            atomic_write_text,
        )

        atomic_write_text(
            out_path, json.dumps(record, indent=2, sort_keys=True) + "\n"
        )
        print(f"# commbench record written to {out_path}", flush=True)
    if os.environ.get("BENCH_CHECK", "") not in ("", "0"):
        raise SystemExit(check_comm_against_committed(record))


# --- serve mode (ISSUE 4: the dynamic-batching inference server) ----------

# Chip default.  The committed CPU capture shrinks it via SERVEBENCH_STEPS
# (same policy as EVALBENCH_STEPS).
SERVE_MEASURE_STEPS = 30


def _serve_source_image(hw: tuple[int, int], min_side: int, max_side: int):
    """A source-resolution image that routes into ``hw`` with a NO-OP
    resize (min side exactly ``min_side``, max exactly ``max_side``), so
    the closed loop measures batching+dispatch, not cv2."""
    h, w = hw
    if h < w:
        shape = (min_side, max_side)
    elif h > w:
        shape = (max_side, min_side)
    else:
        shape = (min_side, min_side)
    rng = np.random.default_rng(2)
    return rng.integers(0, 256, (*shape, 3), dtype=np.uint8)


def _serve_ceiling(engine, hw, batch_size, steps) -> float:
    """In-run detect throughput ceiling on the SAME executable the server
    dispatches (run_eval_bucket's timing pattern: sequential dispatch,
    one hard sync per window) — the denominator of ``vs_ceiling``."""
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (batch_size, *hw, 3), dtype=np.uint8)
    det = engine.dispatch(hw, images)
    _sync_scalar(engine.fetch(det))
    t0 = time.perf_counter()
    for _ in range(steps):
        det = engine.dispatch(hw, images)
    _sync_scalar(engine.fetch(det))
    return batch_size * steps / (time.perf_counter() - t0)


def _serve_closed_loop(server, img, target: int, clients: int) -> dict:
    """Saturating closed loop: ``clients`` threads keep one request each
    in flight until ``target`` requests complete AFTER a one-batch warm
    period; returns steady-state imgs/s + the server's latency stats."""
    import threading

    from batchai_retinanet_horovod_coco_tpu.serve import (
        RequestRejected,
        ServeError,
    )

    stop = threading.Event()
    lock = threading.Lock()
    state = {"completed": 0, "shed": 0, "t_warm": None, "t_end": None,
             "errors": []}
    warm = max(1, clients)

    def client():
        try:
            _client_loop()
        except BaseException as e:
            # Crash channel (thread-error-contract): a silently-dead
            # client skews the closed-loop number, so the crash is
            # recorded and re-raised as a bench failure after the join.
            with lock:
                state["errors"].append(repr(e))
            stop.set()
            raise

    def _client_loop():
        while not stop.is_set():
            try:
                fut = server.submit(img)
            except RequestRejected:
                with lock:
                    state["shed"] += 1
                continue
            except ServeError:
                return
            try:
                fut.result(timeout=600)
            except ServeError:
                return
            except TimeoutError:
                stop.set()
                return
            now = time.perf_counter()
            with lock:
                state["completed"] += 1
                if state["completed"] == warm:
                    state["t_warm"] = now
                if state["completed"] >= warm + target:
                    state["t_end"] = now
                    stop.set()

    t0 = time.perf_counter()
    # watchdog-exempt: bench client threads, stop-event bounded.
    threads = [
        threading.Thread(target=client, daemon=True, name=f"bench-client-{i}")
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    # Wake on target-reached OR every-client-dead (a crashed server ends
    # the clients without setting stop; never sleep out the full hour).
    while not stop.is_set() and any(t.is_alive() for t in threads):
        stop.wait(timeout=1.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    if state["errors"]:
        raise RuntimeError(
            f"bench client thread(s) crashed: {state['errors'][:3]}"
        )
    t_warm = state["t_warm"] or t0
    t_end = state["t_end"] or time.perf_counter()
    measured = max(0, state["completed"] - warm)
    dt = max(t_end - t_warm, 1e-9)
    snap = server.snapshot()
    return {
        "imgs_per_sec": round(measured / dt, 3),
        "completed": state["completed"],
        "closed_loop_shed": state["shed"],
        "clients": clients,
        "p50_ms": snap.get("p50_ms"),
        "p99_ms": snap.get("p99_ms"),
        "deadline_fires": snap.get("deadline_fires"),
    }


def _serve_overload(engine, hw, batch_size, img) -> dict:
    """Open-loop flood against tiny bounded queues: the evidence that
    overload SHEDS (bounded accepted set, bounded p99) instead of
    queueing unboundedly.  Every accepted request must resolve."""
    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectionServer,
        RequestRejected,
        ServeConfig,
    )

    admission = max(4, batch_size)
    bucket_q = max(2, batch_size // 2)
    server = DetectionServer(
        engine,
        ServeConfig(
            max_delay_ms=5.0,
            admission_queue=admission,
            bucket_queue=bucket_q,
            preprocess_workers=1,
        ),
        warmup=False,  # the ceiling measurement already warmed it
    )
    submissions = 6 * (admission + bucket_q)
    accepted, shed = [], 0
    try:
        for _ in range(submissions):
            try:
                accepted.append(server.submit(img))
            except RequestRejected:
                shed += 1
        resolved = sum(1 for f in accepted if f._event.wait(600))
        snap = server.snapshot()
    finally:
        server.close(drain=False)
    return {
        "submitted": submissions,
        "shed_at_submit": shed,
        "accepted": len(accepted),
        "resolved": resolved,
        "completed": snap["completed"],
        "shed_total": snap["shed_total"],
        "p99_ms": snap.get("p99_ms"),
        # The bounded-latency contract: nothing ever queued beyond the
        # configured bounds, and the flood was shed, not buffered.
        "sheds_instead_of_queueing": bool(
            shed > 0 and resolved == len(accepted)
        ),
    }


def _mixed_arrival_schedule(
    n: int, base_rate: float, seed: int = 0
) -> list[float]:
    """The seeded steady → burst → lull schedule, now the SHARED helper
    (ISSUE 18 satellite: utils/arrivals.py — the streaming leg composes
    multi-stream traces from the same seeded family, and unit tests pin
    determinism per seed there)."""
    from batchai_retinanet_horovod_coco_tpu.utils.arrivals import (
        mixed_arrival_schedule,
    )

    return mixed_arrival_schedule(n, base_rate, seed)


def _open_loop_leg(server, images: list, schedule: list[float]) -> dict:
    """Drive one server with the seeded open-loop schedule (request i =
    images[i % len] submitted at schedule[i]); returns p50/p99 over
    completed requests + the server's occupancy/fire counters, and the
    per-request results for the bit-identity cross-check."""
    from batchai_retinanet_horovod_coco_tpu.obs.events import (
        latency_percentiles,
    )
    from batchai_retinanet_horovod_coco_tpu.serve import RequestRejected

    import threading

    t0 = time.perf_counter()
    pending: list[tuple[int, float, object]] = []
    lock = threading.Lock()
    submitted = threading.Event()
    shed = [0]

    errors: list[str] = []

    def submit_on_schedule():
        try:
            for i, due in enumerate(schedule):
                delay = t0 + due - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    fut = server.submit(images[i % len(images)])
                except RequestRejected:
                    with lock:
                        shed[0] += 1
                    continue
                with lock:
                    pending.append((i, time.perf_counter(), fut))
        except BaseException as e:
            # Crash channel (thread-error-contract): a dead submitter
            # skews the open-loop comparison — record and re-raise as a
            # bench failure after the join.
            with lock:
                errors.append(repr(e))
            raise
        finally:
            submitted.set()

    # watchdog-exempt: bench load generator, joined below.
    sub = threading.Thread(
        target=submit_on_schedule, daemon=True, name="bench-open-loop"
    )
    sub.start()
    # Collect CONCURRENTLY with submission, in submission order (batch
    # completion is FIFO here), so each latency is measured at the
    # moment its future resolves — not at drain time.
    latencies, results = [], {}
    j = 0
    while True:
        with lock:
            item = pending[j] if j < len(pending) else None
        if item is None:
            if submitted.is_set() and j >= len(pending):
                break
            time.sleep(0.002)
            continue
        i, t_sub, fut = item
        j += 1
        try:
            results[i] = fut.result(timeout=600)
        except Exception:
            with lock:
                shed[0] += 1
            continue
        latencies.append((time.perf_counter() - t_sub) * 1e3)
    sub.join(timeout=60)
    if errors:
        raise RuntimeError(f"open-loop submitter crashed: {errors}")
    shed = shed[0]
    snap = server.snapshot()
    pct = latency_percentiles(latencies, ps=(50, 99)) if latencies else {}
    return {
        "requests": len(schedule),
        "completed": len(latencies),
        "shed": shed,
        "p50_ms": pct.get("p50_ms"),
        "p99_ms": pct.get("p99_ms"),
        "occupancy_mean": snap.get("occupancy_mean"),
        "batches": snap.get("batches"),
        "deadline_fires": snap.get("deadline_fires"),
        "ready_fires": snap.get("ready_fires"),
        "full_fires": snap.get("full_fires"),
        "_results": results,
    }


def run_continuous_leg(
    make_engine,
    img_for,
    base_rate: float,
    n_requests: int,
    engine_kind: str,
    bit_check=None,
    seed: int = 0,
) -> dict:
    """The continuous-vs-deadline comparison (ISSUE 14): the SAME seeded
    open-loop mixed-arrival schedule against the SAME executable, once
    with the slot-pool dispatch gate (``continuous=True``) and once
    deadline-only.  The contract the committed fields pin: continuous
    mean device batch occupancy strictly above deadline-only, p99 no
    worse (band), and — on the live-engine leg — served detections
    bit-identical to the sequential path on the same artifacts.

    ``make_engine()`` returns the (shared) engine per leg; ``img_for(i)``
    the i-th distinct request payload; ``bit_check(results, images)``
    the in-run sequential cross-check (live engine only).
    """
    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectionServer,
        ServeConfig,
    )

    n_imgs = 4
    images = [img_for(i) for i in range(n_imgs)]
    schedule = _mixed_arrival_schedule(n_requests, base_rate, seed)
    legs = {}
    for mode, continuous in (("deadline", False), ("continuous", True)):
        engine = make_engine()
        server = DetectionServer(
            engine,
            ServeConfig(
                max_delay_ms=10.0,
                continuous=continuous,
                preprocess_workers=2,
            ),
            warmup=False,
        )
        try:
            with obs_trace.span("serve_continuous_leg", mode=mode):
                legs[mode] = _open_loop_leg(server, images, schedule)
        finally:
            server.close(drain=False)
    out = {
        "engine": engine_kind,
        "requests": n_requests,
        "seed": seed,
        "base_rate_per_s": round(base_rate, 3),
        "deadline": {
            k: v for k, v in legs["deadline"].items() if k != "_results"
        },
        "continuous": {
            k: v for k, v in legs["continuous"].items() if k != "_results"
        },
    }
    d_occ = legs["deadline"]["occupancy_mean"] or 0.0
    c_occ = legs["continuous"]["occupancy_mean"] or 0.0
    out["occupancy_gain"] = round(c_occ - d_occ, 4)
    d99, c99 = legs["deadline"]["p99_ms"], legs["continuous"]["p99_ms"]
    if d99 and c99:
        out["p99_ratio"] = round(c99 / d99, 4)
    if bit_check is not None:
        out["bit_identical"] = bit_check(
            legs["continuous"]["_results"], images
        )
    return out


def run_continuous_leg_stub(seed: int = 0) -> dict:
    """The device-independent fast path (``SERVEBENCH_E2E=0`` — the
    servebench-check tripwire): the stub engine with injected device
    time, so the occupancy/p99 contract is checked on every box."""
    from batchai_retinanet_horovod_coco_tpu.serve.stub import (
        StubDetectEngine,
    )

    delay_s, batch = 0.03, 8
    capacity = batch / delay_s

    def img_for(i):
        rng = np.random.default_rng(100 + i)
        return rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)

    return run_continuous_leg(
        make_engine=lambda: StubDetectEngine(
            batch_sizes=(batch,), delay_s=delay_s
        ),
        img_for=img_for,
        base_rate=0.9 * capacity,
        n_requests=int(os.environ.get("SERVEBENCH_CONTINUOUS_N", "240")),
        engine_kind="stub",
        seed=seed,
    )


def run_continuous_leg_e2e(model, state, batch_size: int, seed: int = 0) -> dict:
    """The live-executable leg (the committed capture): flagship bucket,
    arrival rate derived from the in-run detect ceiling, plus the in-run
    bit-identity cross-check — each continuous-mode result compared
    against the SAME artifact driven sequentially (single-request
    assembly through ``assemble_requests`` + ``detections_to_coco``,
    exactly the serve conversion)."""
    import jax as _jax

    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        detections_to_coco,
    )
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        bucket_for_source,
        resize_for_bucket,
    )
    from batchai_retinanet_horovod_coco_tpu.serve import DetectEngine
    from batchai_retinanet_horovod_coco_tpu.serve.batcher import (
        assemble_requests,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.common import ServeRequest

    hw = BUCKET
    min_side, max_side = 800, 1333
    # Sub-prior threshold so the untrained head yields detections and
    # the bit-identity check cannot pass vacuously (the test-suite
    # policy, tests/unit/test_serve.py::_detect_config).
    config = DetectConfig(
        score_threshold=0.001, pre_nms_size=64, max_detections=10
    )
    engine = DetectEngine.from_state(
        model, state, buckets=(hw,), batch_sizes=(batch_size,),
        config=config, min_side=min_side, max_side=max_side,
    )
    engine.warmup()
    ceiling = _serve_ceiling(engine, hw, batch_size, 2)

    def img_for(i):
        h, w = hw
        shape = (
            (min_side, max_side) if h < w
            else (max_side, min_side) if h > w
            else (min_side, min_side)
        )
        rng = np.random.default_rng(100 + i)
        return rng.integers(0, 256, (*shape, 3), dtype=np.uint8)

    def bit_check(results: dict, images: list) -> bool:
        if not results:
            # Anti-vacuity (the sub-prior-threshold policy's sibling): a
            # leg that completed nothing verified nothing.
            print("# continuous-leg bit-identity VACUOUS: no completed "
                  "requests to compare", flush=True)
            return False
        ok = True
        for idx in sorted(set(i % len(images) for i in results)):
            img = images[idx]
            h, w = img.shape[:2]
            bucket = bucket_for_source(
                h, w, min_side, max_side, engine.buckets
            )
            resized, scale = resize_for_bucket(
                img, bucket, min_side, max_side
            )
            req = ServeRequest(0, None, None)
            req.image, req.scale = resized, np.float32(scale)
            req.orig_wh = (w, h)
            assembled = assemble_requests([req], bucket, batch_size)
            det = _jax.device_get(
                engine.dispatch(bucket, assembled.images)
            )
            want = detections_to_coco(
                det, np.array([0], np.int64), assembled.scales,
                assembled.valid, engine.label_to_cat_id,
                image_sizes={0: (w, h)},
            )
            for d in want:
                d.pop("image_id", None)
            got = [results[i] for i in results if i % len(images) == idx]
            if any(g != want for g in got):
                ok = False
                print(
                    f"# continuous-leg bit-identity MISMATCH on image "
                    f"{idx}", flush=True,
                )
        return ok

    n = int(os.environ.get(
        "SERVEBENCH_E2E_N", str(max(12, 3 * batch_size))
    ))
    return run_continuous_leg(
        make_engine=lambda: engine,
        img_for=img_for,
        base_rate=0.85 * ceiling,
        n_requests=n,
        engine_kind="live",
        bit_check=bit_check,
        seed=seed,
    )


def check_continuous_against_committed(fresh: dict | None) -> int:
    """The continuous-batching half of servebench-check (ISSUE 14).
    Relative contracts are device-independent and enforced everywhere:
    continuous occupancy STRICTLY above deadline-only on the same
    schedule, p99 no worse than the band, bit-identity true when the
    live leg ran.  The absolute occupancy floor vs the committed record
    applies when the fresh leg ran the same engine kind (the
    device-class guard's sibling)."""
    try:
        with open(_artifact_path("SERVEBENCH.json")) as f:
            committed = json.load(f).get("continuous")
    except (OSError, ValueError) as e:
        print(f"# servebench-check[continuous]: cannot read baseline: {e}")
        return 1
    if fresh is None:
        print("# servebench-check[continuous]: leg disabled "
              "(SERVEBENCH_CONTINUOUS=0) — the committed record goes "
              "UNCHECKED this run")
        return 0
    rc = 0
    c_occ = fresh["continuous"]["occupancy_mean"] or 0.0
    d_occ = fresh["deadline"]["occupancy_mean"] or 0.0
    if not c_occ > d_occ:
        print(
            f"# servebench-check[continuous]: occupancy {c_occ} not "
            f"strictly above deadline-only {d_occ} on the same seeded "
            "schedule: REGRESSION"
        )
        rc = 1
    band = float(os.environ.get("SERVEBENCH_P99_BAND", "1.25"))
    ratio = fresh.get("p99_ratio")
    if ratio is not None and ratio > band:
        print(
            f"# servebench-check[continuous]: p99 ratio {ratio} above "
            f"the no-worse band {band}: REGRESSION"
        )
        rc = 1
    e2e = fresh.get("e2e") or {}
    if e2e.get("bit_identical") is False:
        print("# servebench-check[continuous]: continuous-mode served "
              "detections diverged from the sequential path: REGRESSION")
        rc = 1
    if committed is None:
        print("# servebench-check[continuous]: committed SERVEBENCH.json "
              "has no continuous record yet — re-capture with "
              "`make servebench`")
        return rc
    if committed.get("engine") == fresh.get("engine"):
        floor = 0.9 * float(
            committed["continuous"].get("occupancy_mean") or 0.0
        )
        if c_occ < floor:
            print(
                f"# servebench-check[continuous]: occupancy {c_occ} "
                f"under the committed floor {round(floor, 4)}: REGRESSION"
            )
            rc = 1
    else:
        print(
            "# servebench-check[continuous]: committed leg ran "
            f"engine={committed.get('engine')}, fresh ran "
            f"{fresh.get('engine')} — absolute floor skipped (relative "
            "contracts enforced above)"
        )
    if committed.get("e2e") and not e2e:
        print(
            "# servebench-check[continuous]: committed live-executable "
            "leg goes UNCHECKED on the SERVEBENCH_E2E=0 fast path — "
            "re-capture with `make servebench` for the full oracle"
        )
    if rc == 0:
        print(
            f"# servebench-check[continuous]: occupancy {c_occ} > "
            f"deadline {d_occ}, p99 ratio {ratio}, "
            f"bit_identical={e2e.get('bit_identical', 'n/a')}: ok"
        )
    return rc


def run_stream_leg(seed: int = 0) -> dict:
    """SERVEBENCH streaming leg (ISSUE 18): N seeded drift-footage
    streams replay a ``multi_stream_schedule`` arrival trace against the
    stub video engine WHILE a mixed single-image schedule rides the same
    server — one slot pool serving both client classes.  Reported:
    frames/sec, per-stream p99, cache hit rate, and the no-starvation
    evidence (every stream frame AND every single-image request
    completes).  Pure stub — device-independent, runs on every box."""
    import threading

    import numpy as np

    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectionServer,
        ServeConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.common import (
        RequestRejected,
        StreamConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.stream import StreamManager
    from batchai_retinanet_horovod_coco_tpu.serve.stub import (
        StubDetectEngine,
        drift_frames,
    )
    from batchai_retinanet_horovod_coco_tpu.utils.arrivals import (
        mixed_arrival_schedule,
        multi_stream_schedule,
    )

    n_streams = int(os.environ.get("SERVEBENCH_STREAMS", "3"))
    frames_per_stream = int(
        os.environ.get("SERVEBENCH_STREAM_FRAMES", "60")
    )
    fps = float(os.environ.get("SERVEBENCH_STREAM_FPS", "30"))
    n_single = int(os.environ.get("SERVEBENCH_STREAM_SINGLES", "40"))
    delta_threshold = 2.0
    engine = StubDetectEngine(batch_sizes=(8,), delay_s=0.01, video=True)
    server = DetectionServer(
        engine, ServeConfig(max_delay_ms=5.0), warmup=False
    )
    manager = StreamManager(
        server, StreamConfig(delta_threshold=delta_threshold)
    )
    schedules = multi_stream_schedule(
        n_streams, frames_per_stream, fps, seed=seed
    )
    # step 1.0 under threshold 2.0 = hits; a cut every 10 frames forces
    # periodic misses — both cache paths exercised in every capture.
    footage = [
        drift_frames(
            seed=seed + 10 * k, n=frames_per_stream, step=1.0,
            cut_every=10,
        )
        for k in range(n_streams)
    ]
    single_schedule = mixed_arrival_schedule(n_single, base_rate=40.0,
                                             seed=seed + 999)
    rng = np.random.default_rng(seed + 500)
    single_imgs = [
        rng.integers(0, 256, (64, 64, 3), dtype=np.uint8)
        for _ in range(4)
    ]

    stream_stats: list[dict | None] = [None] * n_streams
    singles_done = [0]
    errors: list[str] = []
    t0 = time.perf_counter()

    def stream_client(k: int) -> None:
        try:
            sid = manager.open_stream(width=64, height=64)["session"]
            futs = []
            for i, at in enumerate(schedules[k]):
                now = time.perf_counter() - t0
                if at > now:
                    time.sleep(at - now)
                while True:
                    try:
                        futs.append(
                            manager.submit_frame(
                                sid, i, footage[k][i], timeout_s=30.0
                            )
                        )
                        break
                    except RequestRejected as exc:
                        if exc.reason != "stream_backlogged":
                            raise
                        time.sleep(0.002)  # open-loop slip, not a drop
            for f in futs:
                f.result(timeout=30.0)
            stream_stats[k] = manager.close_stream(sid)
        except Exception as e:
            errors.append(f"stream {k}: {e!r}")

    def single_client() -> None:
        try:
            futs = []
            for i, at in enumerate(single_schedule):
                now = time.perf_counter() - t0
                if at > now:
                    time.sleep(at - now)
                try:
                    futs.append(
                        server.submit(
                            single_imgs[i % len(single_imgs)],
                            timeout_s=30.0,
                        )
                    )
                except RequestRejected:
                    continue  # shed = load signal, not starvation
            for f in futs:
                f.result(timeout=30.0)
            singles_done[0] = len(futs)
        except Exception as e:
            errors.append(f"single-image client: {e!r}")

    # watchdog: bench-local load generators, bounded by the join below.
    threads = [
        threading.Thread(target=stream_client, args=(k,), daemon=True)
        for k in range(n_streams)
    ] + [threading.Thread(target=single_client, daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    wall_s = time.perf_counter() - t0
    status = manager.status()
    manager.close()
    server.close(drain=False)
    if errors:
        raise RuntimeError(f"stream leg clients failed: {errors}")

    frames_total = sum(s["frames"] for s in stream_stats if s)
    hits = sum(s["cache_hits"] for s in stream_stats if s)
    per_stream_p99 = [
        s.get("p99_ms") for s in stream_stats if s and s.get("p99_ms")
    ]
    return {
        "engine": "stub",
        "seed": seed,
        "streams": n_streams,
        "frames_per_stream": frames_per_stream,
        "fps": fps,
        "frames_total": frames_total,
        "dropped": n_streams * frames_per_stream - frames_total,
        "frames_per_sec": round(frames_total / wall_s, 2),
        "cache_hit_rate": round(hits / max(1, frames_total), 4),
        "cache_bytes_saved": status["cache_bytes_saved"],
        "per_stream_p99_ms": per_stream_p99,
        "p99_ms_max": max(per_stream_p99) if per_stream_p99 else None,
        "single_image": {
            "requests": n_single,
            "completed": singles_done[0],
        },
    }


def check_stream_against_committed(fresh: dict | None) -> int:
    """The streaming half of servebench-check (ISSUE 18).  Structural
    contracts are device-independent and always enforced: zero dropped
    frames, cache hits present (the delta cache is alive), and the
    mixed single-image traffic completed (no starvation).  The absolute
    p99/throughput comparisons against the committed record apply only
    on a same-engine capture, with a wide band — cross-box wall-clock
    on the stub leg is noisy by design."""
    try:
        with open(_artifact_path("SERVEBENCH.json")) as f:
            committed = json.load(f).get("stream")
    except (OSError, ValueError) as e:
        print(f"# servebench-check[stream]: cannot read baseline: {e}")
        return 1
    if fresh is None:
        print("# servebench-check[stream]: leg disabled "
              "(SERVEBENCH_STREAM=0) — the committed record goes "
              "UNCHECKED this run")
        return 0
    rc = 0
    if fresh.get("dropped"):
        print(f"# servebench-check[stream]: {fresh['dropped']} stream "
              "frames never completed: REGRESSION")
        rc = 1
    if not fresh.get("cache_hit_rate"):
        print("# servebench-check[stream]: zero cache hits on seeded "
              "drift footage — the frame-delta cache is dead: REGRESSION")
        rc = 1
    single = fresh.get("single_image") or {}
    if not single.get("completed"):
        print("# servebench-check[stream]: no single-image request "
              "completed alongside the streams — starvation: REGRESSION")
        rc = 1
    if committed is None:
        print("# servebench-check[stream]: committed SERVEBENCH.json has "
              "no stream record yet — re-capture with `make servebench`")
        return rc
    if committed.get("engine") == fresh.get("engine"):
        band = float(os.environ.get("SERVEBENCH_STREAM_P99_BAND", "3.0"))
        c99, f99 = committed.get("p99_ms_max"), fresh.get("p99_ms_max")
        if c99 and f99 and f99 > band * float(c99):
            print(
                f"# servebench-check[stream]: per-stream p99 {f99}ms "
                f"above {band}x the committed {c99}ms: REGRESSION"
            )
            rc = 1
        floor = 0.5 * float(committed.get("frames_per_sec") or 0.0)
        if float(fresh.get("frames_per_sec") or 0.0) < floor:
            print(
                f"# servebench-check[stream]: frames/sec "
                f"{fresh.get('frames_per_sec')} under the committed "
                f"floor {round(floor, 2)}: REGRESSION"
            )
            rc = 1
    else:
        print(
            "# servebench-check[stream]: committed leg ran engine="
            f"{committed.get('engine')}, fresh ran {fresh.get('engine')} "
            "— absolute bands skipped (structural contracts enforced "
            "above)"
        )
    if rc == 0:
        print(
            f"# servebench-check[stream]: {fresh['frames_total']} frames, "
            f"hit rate {fresh['cache_hit_rate']}, p99max "
            f"{fresh.get('p99_ms_max')}ms, zero dropped: ok"
        )
    return rc


def run_autoscale_leg(seed: int = 0) -> dict:
    """SERVEBENCH autoscale leg (ISSUE 19): a seeded diurnal day with
    one rush-hour spike replays through the REAL control plane —
    FleetRouter + Autoscaler + LocalLauncher over in-process stub
    replicas.  The committed record pins the elasticity contract: the
    fleet grows under the spike (>=1 scale-up, peak >= 2 replicas), p99
    holds through it, every request resolves (zero drops — scale-down
    drains are invisible to clients), and the fleet returns to
    ``min_replicas`` once the day quiets.  Pure stub —
    device-independent, runs (and is checked) on every box."""
    import threading

    import numpy as np

    from batchai_retinanet_horovod_coco_tpu.serve import (
        AutoscalePolicy,
        Autoscaler,
        DetectionServer,
        FleetConfig,
        FleetRouter,
        LocalLauncher,
        LocalReplica,
        RequestRejected,
        ServeConfig,
        ServeError,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.stub import (
        StubDetectEngine,
    )
    from batchai_retinanet_horovod_coco_tpu.utils.arrivals import (
        diurnal_spike_schedule,
    )

    n = int(os.environ.get("SERVEBENCH_AUTOSCALE_REQUESTS", "240"))
    base_rate = float(os.environ.get("SERVEBENCH_AUTOSCALE_RATE", "12"))
    clients = 16

    def factory(rid):
        server = DetectionServer(
            StubDetectEngine(delay_s=0.06),
            ServeConfig(max_delay_ms=2.0, preprocess_workers=1),
            replica_id=rid,
        )
        return LocalReplica(server)

    launcher = LocalLauncher(
        factory, drain_timeout_s=15.0, prefix="bench-scale"
    )
    seed_replica = factory("bench-scale-seed")
    launcher.adopt(seed_replica)
    router = FleetRouter(
        [seed_replica],
        FleetConfig(poll_interval_s=0.1, default_timeout_s=30.0),
    )
    # The chaos.py --autoscale leg proved this band/cadence against the
    # same 60 ms stub: off-peak sits inside the band, the 4x spike
    # breaches high, the post-day quiet breaches low back to min.
    policy = AutoscalePolicy(
        min_replicas=1, max_replicas=3,
        occupancy_low=0.15, occupancy_high=0.5,
        for_s=0.4, up_cooldown_s=1.0, down_cooldown_s=2.0,
        interval_s=0.1,
    )
    scaler = Autoscaler(router, policy, launcher).start()

    times = diurnal_spike_schedule(
        n, base_rate=base_rate, seed=seed, period_s=12.0,
        amplitude=0.5, spikes=((0.55, 0.4, 4.0),),
    )
    img = np.zeros((64, 64, 3), np.uint8)
    lock = threading.Lock()
    next_i = [0]
    latencies: list[float] = []
    counts = {"ok": 0, "shed": 0, "dropped": 0}

    def client():
        try:
            while True:
                with lock:
                    i = next_i[0]
                    if i >= len(times):
                        return
                    next_i[0] += 1
                wait = times[i] - (time.perf_counter() - t0)
                if wait > 0:
                    time.sleep(wait)  # open-loop pacing; busy = slip
                t1 = time.perf_counter()
                try:
                    router.detect(img)
                    with lock:
                        counts["ok"] += 1
                        latencies.append(
                            (time.perf_counter() - t1) * 1e3
                        )
                except RequestRejected:
                    with lock:
                        counts["shed"] += 1
                except ServeError:
                    with lock:
                        counts["dropped"] += 1
        except Exception as e:  # crash channel: an unresolved request
            print(f"# autoscale leg client crashed: {e!r}", flush=True)
            with lock:
                counts["dropped"] += 1
            raise

    # watchdog: bench-local load generators, bounded by the join below.
    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()

    # Sampler doubles as the join loop: the replica-count trajectory vs
    # offered load is the record's elasticity evidence.
    trajectory: list[list[float]] = []
    deadline = t0 + times[-1] + 120.0
    while any(t.is_alive() for t in threads):
        if time.perf_counter() > deadline:
            break
        with lock:
            offered = next_i[0]
        trajectory.append([
            round(time.perf_counter() - t0, 2),
            float(offered),
            float(router.active_replica_count()),
        ])
        time.sleep(0.5)
    for t in threads:
        t.join(timeout=10)
    hung = sum(t.is_alive() for t in threads)

    # The day is over: wait for the scale-down half of the contract —
    # the quiet fleet drains back to min_replicas with zero drops.
    quiet_deadline = time.perf_counter() + 60.0
    while time.perf_counter() < quiet_deadline:
        st = scaler.status()
        if (router.active_replica_count() <= policy.min_replicas
                and st["scale_downs"] >= 1 and not st["draining"]):
            break
        trajectory.append([
            round(time.perf_counter() - t0, 2),
            float(n),
            float(router.active_replica_count()),
        ])
        time.sleep(0.25)
    final_replicas = router.active_replica_count()
    st = scaler.status()
    decisions = [
        {"decision": d["decision"], "reason": d["reason"],
         "delta": d["delta"]}
        for d in scaler.decisions
    ]
    scaler.stop()
    router.close(close_replicas=True)

    peak = max([s[2] for s in trajectory] or [1.0])
    p99 = (
        round(float(np.percentile(np.asarray(latencies), 99)), 2)
        if latencies else None
    )
    return {
        "engine": "stub",
        "seed": seed,
        "requests": n,
        "completed": counts["ok"],
        "shed": counts["shed"],
        "dropped": counts["dropped"] + hung,
        "p99_ms": p99,
        "scaled_up": st["scale_ups"],
        "scaled_down": st["scale_downs"],
        "capped": st["capped"],
        "peak_replicas": int(peak),
        "final_replicas": int(final_replicas),
        "min_replicas": policy.min_replicas,
        "max_replicas": policy.max_replicas,
        "decisions": decisions,
        # Downsampled so the committed artifact stays reviewable.
        "trajectory": trajectory[::2],
    }


def check_autoscale_against_committed(fresh: dict | None) -> int:
    """The autoscaling half of servebench-check (ISSUE 19).  Structural
    contracts are device-independent and always enforced: zero dropped
    requests (scale-down drains never kill in-flight work), the fleet
    grew under the spike, and it returned to min_replicas once the day
    quieted.  The absolute p99 band against the committed record
    applies only same-engine, wide — stub wall-clock is noisy."""
    try:
        with open(_artifact_path("SERVEBENCH.json")) as f:
            committed = json.load(f).get("autoscale")
    except (OSError, ValueError) as e:
        print(f"# servebench-check[autoscale]: cannot read baseline: {e}")
        return 1
    if fresh is None:
        print("# servebench-check[autoscale]: leg disabled "
              "(SERVEBENCH_AUTOSCALE=0) — the committed record goes "
              "UNCHECKED this run")
        return 0
    rc = 0
    if fresh.get("dropped"):
        print(f"# servebench-check[autoscale]: {fresh['dropped']} "
              "requests never resolved across scaling: REGRESSION")
        rc = 1
    if not fresh.get("scaled_up"):
        print("# servebench-check[autoscale]: the fleet never scaled "
              "up under the spike — the control loop is dead: "
              "REGRESSION")
        rc = 1
    if fresh.get("peak_replicas", 0) < 2:
        print("# servebench-check[autoscale]: peak replica count "
              f"{fresh.get('peak_replicas')} — the spike never grew "
              "the fleet: REGRESSION")
        rc = 1
    if (not fresh.get("scaled_down")
            or fresh.get("final_replicas") != fresh.get("min_replicas")):
        print("# servebench-check[autoscale]: fleet ended at "
              f"{fresh.get('final_replicas')} replicas (min "
              f"{fresh.get('min_replicas')}) — never returned to min "
              "after the day quieted: REGRESSION")
        rc = 1
    if committed is None:
        print("# servebench-check[autoscale]: committed SERVEBENCH.json "
              "has no autoscale record yet — re-capture with "
              "`make servebench`")
        return rc
    if committed.get("engine") == fresh.get("engine"):
        band = float(
            os.environ.get("SERVEBENCH_AUTOSCALE_P99_BAND", "3.0")
        )
        c99, f99 = committed.get("p99_ms"), fresh.get("p99_ms")
        if c99 and f99 and f99 > band * float(c99):
            print(
                f"# servebench-check[autoscale]: p99 {f99}ms above "
                f"{band}x the committed {c99}ms — latency not held "
                "through the spike: REGRESSION"
            )
            rc = 1
    else:
        print(
            "# servebench-check[autoscale]: committed leg ran engine="
            f"{committed.get('engine')}, fresh ran "
            f"{fresh.get('engine')} — absolute bands skipped "
            "(structural contracts enforced above)"
        )
    if rc == 0:
        print(
            f"# servebench-check[autoscale]: {fresh['completed']} ok / "
            f"{fresh['shed']} shed, peak {fresh['peak_replicas']} "
            f"replicas, {fresh['scaled_up']} up / "
            f"{fresh['scaled_down']} down, p99 {fresh.get('p99_ms')}ms, "
            "zero dropped: ok"
        )
    return rc


def _scrape_telemetry(server) -> dict:
    """Scrape the live-telemetry plane ONCE per measurement window
    (ISSUE 9 satellite): mount the real HTTP frontend over the just-
    measured server, GET /metrics + /healthz, and cross-check the
    registry-derived p99/shed/completed numbers against the server's own
    snapshot.  The two sources read the SAME LatencyStats window through
    different code paths (Prometheus encode → text → parse vs direct
    snapshot), so any disagreement is a real exposition bug —
    ``consistent`` is recorded in the bench line and announced, never
    silently dropped."""
    import threading
    import urllib.error
    import urllib.request

    from batchai_retinanet_horovod_coco_tpu.obs import telemetry, watchdog
    from batchai_retinanet_horovod_coco_tpu.serve import serve_http

    httpd = serve_http(server, port=0)
    hb = watchdog.register("bench-telemetry-scrape")
    thread = threading.Thread(
        # Stdlib target: crashes surface as the scrape's urlopen failure.
        target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True, name="bench-telemetry-scrape",
    )
    thread.start()
    try:
        host, port = httpd.server_address[:2]
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=30
        ) as r:
            text = r.read().decode()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=30
            ) as r:
                health_code = r.status
                health = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:  # 503 = stalled (still data)
            health_code = e.code
            health = json.loads(e.read().decode())
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=10)
        hb.close()

    types, samples = telemetry.parse_exposition(text)
    snap = server.snapshot()
    p99 = samples.get('serve_request_latency_ms{quantile="0.99"}')
    shed = sum(
        v for k, v in samples.items() if k.startswith("serve_shed_total")
    )
    completed = samples.get("serve_requests_completed_total")
    problems = []
    if types.get("serve_request_latency_ms") != "summary":
        problems.append("latency family missing/untyped")
    if completed != snap["completed"]:
        problems.append(
            f"completed {completed} != snapshot {snap['completed']}"
        )
    if shed != snap["shed_total"]:
        problems.append(f"shed {shed} != snapshot {snap['shed_total']}")
    snap_p99 = snap.get("p99_ms")
    if (p99 is None) != (snap_p99 is None):
        problems.append(f"p99 presence mismatch ({p99} vs {snap_p99})")
    elif p99 is not None and abs(p99 - snap_p99) > max(0.5, 0.01 * snap_p99):
        problems.append(f"p99 {p99} != snapshot {snap_p99}")
    if problems:
        print(f"# telemetry-consistency MISMATCH: {problems}", flush=True)
    return {
        "registry_p99_ms": p99,
        "registry_shed_total": shed,
        "registry_completed": completed,
        "healthz_status": health_code,
        "healthz_ok": health_code == 200 and health.get("status") == "ok",
        "consistent": not problems,
    }


def run_serve_bucket(
    model, state, batch_size: int, hw: tuple[int, int], measure_steps: int,
    overload: bool,
) -> dict:
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectEngine,
        DetectionServer,
        ServeConfig,
    )

    min_side, max_side = 800, 1333  # the flagship resize rule behind BUCKET
    engine = DetectEngine.from_state(
        model, state, buckets=(hw,), batch_sizes=(batch_size,),
        config=DetectConfig(), min_side=min_side, max_side=max_side,
    )
    engine.warmup()
    ceiling = _serve_ceiling(
        engine, hw, batch_size, max(1, measure_steps // 2)
    )
    img = _serve_source_image(hw, min_side, max_side)
    server = DetectionServer(
        engine,
        ServeConfig(
            max_delay_ms=10.0,
            admission_queue=4 * batch_size,
            bucket_queue=4 * batch_size,
            preprocess_workers=2,
        ),
        warmup=False,
    )
    try:
        closed = _serve_closed_loop(
            server, img,
            target=measure_steps * batch_size,
            clients=max(2, 2 * batch_size),
        )
        # One /metrics scrape per window, against the still-open server
        # (the closed loop has joined its clients, so the stats are
        # frozen and the two sources must agree exactly).
        telem = _scrape_telemetry(server)
    finally:
        server.close(drain=False)
    out = {
        "batch": batch_size,
        "detect_ceiling_imgs_per_sec": round(ceiling, 3),
        "vs_ceiling": round(closed["imgs_per_sec"] / max(ceiling, 1e-9), 3),
        **closed,
        "telemetry": telem,
    }
    if overload:
        with obs_trace.span("serve_overload", bucket=f"{hw[0]}x{hw[1]}"):
            out["overload"] = _serve_overload(engine, hw, batch_size, img)
    return out


# ---------------------------------------------------------------------------
# Fleet availability leg (ISSUE 12): real fleet machinery, stub replicas
# ---------------------------------------------------------------------------


def run_fleet_leg() -> dict:
    """Kill-a-replica availability + canary rollback on the REAL fleet
    router (serve/fleet.py) over in-process stub replicas.

    No device work at all — the measurand is the ROUTER's mechanics
    (availability under replica death, bounded re-dispatch, exactly-once
    canary rollback), which are device-independent, so the leg runs
    identically on the chip and on a CPU check box.  The contract the
    committed ``fleet`` fields pin: every submitted request RESOLVES
    (availability 1.0 — completes or sheds with a reason, zero hangs),
    and post-kill completion stays at or above the surviving capacity
    share ((N-1)/N).
    """
    import threading

    import numpy as np

    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectionServer,
        FleetConfig,
        FleetRouter,
        LocalReplica,
        RequestRejected,
        RequestTimeout,
        ServeConfig,
        ServeError,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.stub import (
        StubDetectEngine,
    )

    n_replicas = 3
    servers = [
        DetectionServer(
            StubDetectEngine(delay_s=0.01),
            ServeConfig(max_delay_ms=2.0, preprocess_workers=1),
            replica_id=f"bench-r{i}",
        )
        for i in range(n_replicas)
    ]
    router = FleetRouter(
        [LocalReplica(s) for s in servers],
        FleetConfig(
            poll_interval_s=0.05, default_timeout_s=20.0,
            canary_weight=0.5, canary_p99_factor=3.0,
            canary_for_s=0.2, canary_poll_s=0.05,
        ),
    )
    img = np.zeros((64, 64, 3), np.uint8)
    total, clients = 120, 4
    kill_at = total // 2
    lock = threading.Lock()
    counts = {"ok": 0, "shed": 0, "timeout": 0, "failed": 0}
    post_kill = {"ok": 0, "total": 0}
    issued = [0]
    killed = [False]

    def client():
        try:
            while True:
                with lock:
                    if issued[0] >= total:
                        return
                    issued[0] += 1
                    fire = issued[0] == kill_at and not killed[0]
                    if fire:
                        killed[0] = True
                if fire:
                    # The in-process SIGKILL equivalent: the victim's
                    # threads stop and every subsequent submit raises —
                    # the router must breaker it and re-dispatch.
                    servers[0].close(drain=False)
                try:
                    router.detect(img)
                    out = "ok"
                except RequestRejected:
                    out = "shed"
                except RequestTimeout:
                    out = "timeout"
                except ServeError:
                    out = "failed"
                with lock:
                    counts[out] += 1
                    if killed[0]:
                        post_kill["total"] += 1
                        post_kill["ok"] += out == "ok"
        except Exception as e:  # crash channel: an unresolved request
            print(f"# fleet leg client crashed: {e!r}", flush=True)
            raise

    # watchdog: bench-local load generators, bounded by the join below.
    threads = [
        threading.Thread(target=client, daemon=True) for _ in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)

    # Canary micro-leg: a visibly slow canary joins; its monitor (real
    # poll thread, aggressive cadence) must fire exactly one rollback.
    # 250 ms of injected device time: the serve stack's light-load
    # latency floor (~60 ms — the dispatcher's idle-flush poll) would
    # mask a smaller regression under the 3x ratio gate.
    canary_server = DetectionServer(
        StubDetectEngine(delay_s=0.25),
        ServeConfig(max_delay_ms=2.0, preprocess_workers=1),
        replica_id="bench-canary",
    )
    router.add_canary(LocalReplica(canary_server), start_monitor=True)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            router.detect(img)
        except ServeError:
            pass
        if router.status()["canary_rollbacks"] >= 1:
            break
    status = router.status()

    # Federated-vs-local p99 consistency (ISSUE 15): with traffic
    # quiesced, one federation sweep — each surviving replica's OWN
    # windowed p99 must round-trip the fleet scrape EXACTLY (both sides
    # read the same LatencyStats window through the same percentile
    # helper; any delta means federation re-labeled or lost samples).
    router.scrape_metrics_once()
    fed = router.federated_snapshot()
    fed_checked = 0
    fed_max_delta = 0.0
    fed_consistent = True
    for s in servers[1:]:  # servers[0] was killed mid-leg
        local = s.telemetry.snapshot().get("serve_request_latency_ms.p99")
        if local is None:
            continue
        fed_p99 = fed.get(
            "serve_request_latency_ms"
            f'{{quantile="0.99",replica="{s.replica_id}"}}'
        )
        fed_checked += 1
        if fed_p99 is None:
            fed_consistent = False
            print(
                f"# fleet leg: replica {s.replica_id} missing from the "
                "federated scrape", flush=True,
            )
            continue
        delta = abs(float(fed_p99) - float(local))
        fed_max_delta = max(fed_max_delta, delta)
        if delta > 1e-9:
            fed_consistent = False
            print(
                f"# fleet leg: federated p99 {fed_p99} != local {local} "
                f"on {s.replica_id}", flush=True,
            )

    router.close()
    for s in servers:
        s.close(drain=False)
    canary_server.close(drain=False)

    resolved = sum(counts.values())
    return {
        "replicas": n_replicas,
        "requests": issued[0],
        "completed": counts["ok"],
        "shed": counts["shed"],
        "timeout": counts["timeout"],
        "failed": counts["failed"],
        "unresolved": issued[0] - resolved,
        # THE availability claim: 1.0 = every request completed or shed
        # with a reason — nothing hung, nothing silently dropped.
        "availability": round(resolved / max(1, issued[0]), 4),
        "post_kill_ok_ratio": round(
            post_kill["ok"] / max(1, post_kill["total"]), 4
        ),
        "capacity_share_floor": round((n_replicas - 1) / n_replicas, 4),
        "redispatches": status["redispatches"],
        "breaker_opens": status["breaker_opens"],
        "canary_rollbacks": status["canary_rollbacks"],
        # Metrics-federation consistency (ISSUE 15): the fleet-scraped,
        # replica-labeled p99 equals each surviving replica's own
        # registry value on a quiesced fleet.
        "federated_p99_consistent": fed_consistent and fed_checked > 0,
        "federated_replicas_checked": fed_checked,
        "federated_p99_max_delta_ms": round(fed_max_delta, 6),
    }


def check_fleet_against_committed(fresh: dict | None) -> int:
    """The fleet half of servebench-check.  Device-class guard does not
    apply: the leg is stub-based and device-independent, so the bands
    hold everywhere — availability is an exact contract (1.0), post-kill
    completion must clear the (N-1)/N capacity-share floor, and the
    canary gate must have fired exactly once."""
    try:
        with open(_artifact_path("SERVEBENCH.json")) as f:
            committed = json.load(f).get("fleet")
    except (OSError, ValueError) as e:
        print(f"# servebench-check[fleet]: cannot read baseline: {e}")
        return 1
    if committed is None:
        print("# servebench-check[fleet]: committed SERVEBENCH.json has no "
              "fleet record yet — re-capture with `make servebench`")
        return 0
    if fresh is None:
        print("# servebench-check[fleet]: fleet leg disabled "
              "(SERVEBENCH_FLEET=0) — the committed fleet record goes "
              "UNCHECKED this run; re-enable it for the real tripwire")
        return 0
    rc = 0
    if fresh["availability"] < float(committed.get("availability", 1.0)):
        print(
            f"# servebench-check[fleet]: availability regressed "
            f"{committed.get('availability')} -> {fresh['availability']} "
            "(requests hung or were silently dropped): REGRESSION"
        )
        rc = 1
    floor = float(committed.get("capacity_share_floor", 2 / 3))
    if fresh["post_kill_ok_ratio"] < floor:
        print(
            f"# servebench-check[fleet]: post-kill completion "
            f"{fresh['post_kill_ok_ratio']} below the (N-1)/N capacity "
            f"share {floor}: REGRESSION"
        )
        rc = 1
    if fresh["canary_rollbacks"] != 1:
        print(
            f"# servebench-check[fleet]: expected exactly 1 canary "
            f"rollback, measured {fresh['canary_rollbacks']}: REGRESSION"
        )
        rc = 1
    if fresh.get("federated_p99_consistent") is False:
        print(
            "# servebench-check[fleet]: federated /metrics p99 diverged "
            "from the replicas' own registries "
            f"(max delta {fresh.get('federated_p99_max_delta_ms')} ms): "
            "REGRESSION"
        )
        rc = 1
    if rc == 0:
        print(
            f"# servebench-check[fleet]: availability "
            f"{fresh['availability']}, post-kill {fresh['post_kill_ok_ratio']}"
            f" >= {floor}, canary rollbacks 1: ok"
        )
    return rc


def check_serve_against_committed(
    value: float, device_kind: str, fleet: dict | None = None,
    continuous: dict | None = None, stream: dict | None = None,
    autoscale: dict | None = None,
) -> int:
    """servebench-check: fresh flagship closed-loop SERVE rate vs the
    committed SERVEBENCH.json — same floor/device policy as bench-check
    (``_check_floor``) — plus the fleet availability band (ISSUE 12),
    the continuous-batching occupancy/p99 contract (ISSUE 14), the
    streaming-session contract (ISSUE 18), and the autoscale
    elasticity contract (ISSUE 19)."""
    try:
        with open(_artifact_path("SERVEBENCH.json")) as f:
            committed = json.load(f)
        committed_value = float(committed["value"])
    except (OSError, KeyError, ValueError) as e:
        print(f"# servebench-check: cannot read committed baseline: {e}")
        return 1
    rc = _check_floor(
        "servebench-check",
        value,
        committed_value,
        str(committed.get("device_kind", "")) or None,
        device_kind,
    )
    return max(
        rc,
        check_fleet_against_committed(fleet),
        check_continuous_against_committed(continuous),
        check_stream_against_committed(stream),
        check_autoscale_against_committed(autoscale),
    )


def run_serve_mode() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    measure_steps = int(
        os.environ.get("SERVEBENCH_STEPS", str(SERVE_MEASURE_STEPS))
    )
    sweep = os.environ.get("BENCH_SWEEP", "1") not in ("", "0")
    overload = os.environ.get("SERVEBENCH_OVERLOAD", "1") not in ("", "0")
    model, state = _eval_model_and_state()
    device_kind = jax.devices()[0].device_kind

    per_bucket: dict[str, dict] = {}
    value = None
    for hw, _share in sweep_buckets():
        if not sweep and hw != BUCKET:
            continue
        try:
            r = run_serve_bucket(
                model, state, batch_size, hw, measure_steps, overload
            )
        except Exception as e:
            oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
            if batch_size <= 2 or not oom:
                raise
            print(f"# batch {batch_size} OOM at {hw}; retrying at 2", flush=True)
            r = run_serve_bucket(model, state, 2, hw, measure_steps, overload)
        per_bucket[f"{hw[0]}x{hw[1]}"] = r
        if hw == BUCKET:
            value = r["imgs_per_sec"]

    out = {
        "metric": "serve_images_per_sec_per_chip",
        "mode": "serve",
        "value": value,
        "unit": "images/sec/chip",
        "device_kind": device_kind,
        "measure_steps": measure_steps,
        "per_bucket": per_bucket,
    }
    # Fleet availability leg (ISSUE 12): stub-based (device-independent),
    # cheap — on by default; SERVEBENCH_FLEET=0 skips it.
    fleet = None
    if os.environ.get("SERVEBENCH_FLEET", "1") not in ("", "0"):
        fleet = run_fleet_leg()
        out["fleet"] = fleet
    # Continuous-vs-deadline leg (ISSUE 14): the same seeded open-loop
    # mixed-arrival schedule against the same executable in both
    # batching modes.  SERVEBENCH_E2E=1 (capture default) runs it on the
    # live flagship executable with the in-run bit-identity cross-check;
    # SERVEBENCH_E2E=0 (the check target's fast path) runs the
    # device-independent stub leg.  SERVEBENCH_CONTINUOUS=0 skips.
    cont = None
    if os.environ.get("SERVEBENCH_CONTINUOUS", "1") not in ("", "0"):
        with obs_trace.span("serve_continuous_vs_deadline"):
            # The stub comparison ALWAYS runs (device-independent — the
            # occupancy/p99 contract is checkable on every box); the
            # live-executable leg with the in-run bit-identity
            # cross-check rides along unless SERVEBENCH_E2E=0 (the
            # check target's fast path).
            cont = run_continuous_leg_stub()
            if os.environ.get("SERVEBENCH_E2E", "1") not in ("", "0"):
                cont["e2e"] = run_continuous_leg_e2e(
                    model, state, batch_size
                )
        out["continuous"] = cont
    # Streaming leg (ISSUE 18): seeded drift streams + mixed single-image
    # traffic through StreamManager over the stub video engine —
    # device-independent, so it runs (and is checked) on every box.
    # SERVEBENCH_STREAM=0 skips.
    stream = None
    if os.environ.get("SERVEBENCH_STREAM", "1") not in ("", "0"):
        with obs_trace.span("serve_stream_leg"):
            stream = run_stream_leg()
        out["stream"] = stream
    # Autoscale leg (ISSUE 19): the seeded diurnal/spike day through the
    # real control plane (FleetRouter + Autoscaler) over stub replicas —
    # device-independent.  SERVEBENCH_AUTOSCALE=0 skips.
    autoscale = None
    if os.environ.get("SERVEBENCH_AUTOSCALE", "1") not in ("", "0"):
        with obs_trace.span("serve_autoscale_leg"):
            autoscale = run_autoscale_leg()
        out["autoscale"] = autoscale
    att = _trace_attribution()
    if att is not None:
        out["attribution"] = att
    print(json.dumps(out), flush=True)

    if os.environ.get("BENCH_CHECK", "") not in ("", "0"):
        raise SystemExit(
            check_serve_against_committed(
                value, device_kind, fleet, cont, stream, autoscale
            )
        )


def run_train_mode() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    sweep = os.environ.get("BENCH_SWEEP", "1") not in ("", "0")
    # BENCH_STEPS: train-mode twin of EVALBENCH_STEPS/SERVEBENCH_STEPS —
    # the chip default stays MEASURE_STEPS; a CPU-fallback capture (dead
    # tunnel) shrinks the window so the record exists at all.
    measure_steps = int(os.environ.get("BENCH_STEPS", str(MEASURE_STEPS)))

    flag_batch, (ips, mfu, windows) = _run_with_oom_retry(
        batch_size, BUCKET, measure_steps
    )
    baseline = first_recorded_bench()
    value = round(ips, 3)
    out = {
        "metric": "train_images_per_sec_per_chip",
        "value": value,
        "unit": "images/sec/chip",
        # A consumer must be able to tell a chip number from a CPU-fallback
        # capture (a session can come up with no TPU platform at all, in
        # which case the probe legitimately passes on the CPU backend).
        "device_kind": jax.devices()[0].device_kind,
        "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # Same-run noise floor: two disjoint timed windows of the same
        # compiled step.  A cross-round delta inside this spread is noise.
        "window_rates": [round(w, 3) for w in windows],
        "noise_pct": round(
            abs(windows[0] - windows[1]) / value * 100, 2
        ),
    }
    # Which kernel schedule produced this number (tune/): the registry
    # artifact the step's kernel params resolved from, or the built-in
    # defaults on an untuned device — BENCH_r06+ records must say which.
    from batchai_retinanet_horovod_coco_tpu.tune import provenance

    out["schedule"] = provenance(out["device_kind"])

    # Numerics-plane overhead evidence (ISSUE 10): re-measure the SAME
    # flagship config with the in-step summary fused in and state the
    # on-vs-off delta in the committed line.  BENCH_NUMERICS=0 skips
    # (the check targets — the extra AOT compile is minutes on CPU).
    if os.environ.get("BENCH_NUMERICS", "1") not in ("", "0"):
        ips_on, _mfu_on, _win_on = run_bench(
            flag_batch, BUCKET, measure_steps, numerics=True
        )
        out["numerics_overhead"] = {
            "imgs_per_sec_off": value,
            "imgs_per_sec_on": round(ips_on, 3),
            "delta_pct": round((value - ips_on) / value * 100, 2),
            "note": (
                "in-step numerics summary (obs/numerics.py) on vs off; "
                "delta within noise_pct is noise.  Disabled path is "
                "structurally free (identical compiled step)"
            ),
        }

    att = _trace_attribution()
    if att is not None:
        out["attribution"] = att
    if sweep:
        # Print the flagship-only line BEFORE the (minutes-long) sweep of
        # the other buckets: a consumer that reads the LAST line gets the
        # full sweep result, while a harness that kills the process on a
        # timeout still finds a complete, valid flagship line.
        print(json.dumps(out), flush=True)
        buckets = sweep_buckets()
        per_bucket = {f"{BUCKET[0]}x{BUCKET[1]}": value}
        rates = {BUCKET: ips}
        # Effective per-bucket batch: an OOM retry drops a bucket to batch
        # 2, whose rate is NOT comparable (batch 1-2 halves MFU — see
        # BUCKETBENCH.json batch_scaling) — record it so a mixed-batch
        # weighted_mix is visible instead of silently understated.
        bucket_batch = {f"{BUCKET[0]}x{BUCKET[1]}": flag_batch}
        for hw, _share in buckets:
            if hw == BUCKET:
                continue
            b_eff, (b_ips, _b_mfu, _b_windows) = _run_with_oom_retry(
                batch_size, hw, min(SWEEP_MEASURE_STEPS, measure_steps)
            )
            rates[hw] = b_ips
            per_bucket[f"{hw[0]}x{hw[1]}"] = round(b_ips, 3)
            bucket_batch[f"{hw[0]}x{hw[1]}"] = b_eff
        # Mix-weighted throughput: steps are drawn per bucket with the
        # COCO aspect shares, so the average COST per image is the
        # share-weighted mean of 1/rate (harmonic mix), not of the rates.
        total_share = sum(s for _, s in buckets)
        cost = sum(s / rates[hw] for hw, s in buckets) / total_share
        out["per_bucket"] = per_bucket
        out["weighted_mix"] = round(1.0 / cost, 3)
        out["mix_shares"] = {
            f"{hw[0]}x{hw[1]}": s for hw, s in buckets
        }
        if len(set(bucket_batch.values())) > 1:
            out["per_bucket_batch"] = bucket_batch
            out["weighted_mix_caveat"] = (
                "buckets measured at differing batch sizes (OOM retry); "
                "weighted_mix mixes non-comparable rates"
            )
        att = _trace_attribution()  # now includes the sweep buckets' spans
        if att is not None:
            out["attribution"] = att

    print(json.dumps(out))

    if os.environ.get("BENCH_CHECK", "") not in ("", "0"):
        raise SystemExit(
            check_against_committed(value, out["device_kind"])
        )


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--mode", choices=("train", "eval", "serve", "comm"),
        default="train",
        help="train = flagship SPMD train step; eval = detect/NMS fast "
             "path (per-bucket AOT detect + postprocess-only + "
             "sequential-vs-pipelined e2e); serve = dynamic-batching "
             "inference server (serve/) under a saturating closed loop "
             "+ an overload shed leg, vs the in-run detect ceiling; "
             "comm = gradient-compression subsystem (comm/) on a "
             "forced virtual CPU mesh — bytes-on-wire vs exact, "
             "step-time delta, parity drift (COMMBENCH.json)",
    )
    ap.add_argument(
        "--trace", "--obs-trace", action="store_true", dest="trace",
        help="record obs trace spans (AOT compiles, timed windows, and "
             "for --mode eval the full three-stage e2e pipeline) and "
             "write a Perfetto-loadable Chrome trace artifact per bench "
             "mode into --obs-dir (--obs-trace is the train.py spelling, "
             "accepted here too)",
    )
    ap.add_argument(
        "--obs-dir", default="artifacts/obs",
        help="where --trace writes its trace artifact",
    )
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.configure(
            args.obs_dir, process_label=f"bench-{args.mode}"
        )

    # Availability probe BEFORE any in-process device work: a dead tunnel
    # can hang backend init, which only a subprocess probe can bound.
    if os.environ.get("BENCH_PROBE", "1") not in ("", "0"):
        attempts, err = probe_device()
        if err is not None:
            raise emit_unreachable(args.mode, attempts, err, phase="probe")

    try:
        if args.trace:
            # Device metadata into the trace AFTER the probe cleared the
            # backend (an in-process jax.devices() before it could hang
            # on a dead tunnel): the perf report resolves device_kind —
            # hence the MFU peak — from the trace alone.
            obs_trace.instant(
                "run_meta", device_kind=jax.devices()[0].device_kind
            )
        if args.mode == "eval":
            run_eval_mode()
        elif args.mode == "serve":
            run_serve_mode()
        elif args.mode == "comm":
            run_comm_mode()
        else:
            run_train_mode()
    except SystemExit:
        raise
    except Exception as e:
        # The probe can pass and the tunnel die mid-run; that is still an
        # outage, not a bench bug — classify it.  Real errors propagate.
        if is_unavailable_error(e):
            raise emit_unreachable(
                args.mode, 1, str(e), phase="mid-run"
            ) from None
        raise
    finally:
        if args.trace:
            obs_trace.export()
            merged = obs_trace.merge_traces(
                out_name=f"bench_{args.mode}_trace.json"
            )
            # "#"-prefixed: the bench's stdout contract is JSON lines plus
            # comment lines; a consumer parsing first/last JSON is safe.
            print(f"# trace written to {merged}", flush=True)
            # Perf-doctor report next to the trace (never raises — a
            # failed analysis is one structured stderr line, not a bench
            # failure).
            try:
                from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
                    auto_emit,
                )

                # events_name=None: bench writes no events JSONL, and a
                # shared obs dir may hold a previous TRAIN run's
                # metrics.jsonl — its header/compile/stall records must
                # not be attributed to this bench.
                report = auto_emit(
                    args.obs_dir,
                    trace_name=f"bench_{args.mode}_trace.json",
                    out_name=f"PERF_REPORT_bench_{args.mode}.json",
                    events_name=None,
                )
            except Exception as e:
                print(f"# perf report failed: {e!r}", flush=True)
                report = None
            if report:
                print(f"# perf report written to {report}", flush=True)


if __name__ == "__main__":
    main()
