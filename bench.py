"""Benchmark: flagship train-step throughput, printed as ONE JSON line.

Measures images/sec/chip for the full jitted SPMD training step (forward,
on-device target assignment, focal + smooth-L1 losses, backward, optimizer
update) on RetinaNet ResNet-50-FPN at the reference's flagship resolution
bucket (800x1344, BASELINE.json:10), bf16 compute.

``vs_baseline``: the reference's own throughput was never recorded
(BASELINE.json "published": {}, see BASELINE.md), so the ratio is computed
against the first recorded bench of this rebuild (BENCH_r1.json) when
present, else 1.0 — i.e. it tracks round-over-round improvement.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BUCKET = (800, 1344)
WARMUP_STEPS = 3
MEASURE_STEPS = 10


def make_batch(batch_size: int, hw: tuple[int, int], max_gt: int = 100):
    rng = np.random.default_rng(0)
    h, w = hw
    gt_boxes = np.zeros((batch_size, max_gt, 4), np.float32)
    gt_labels = np.zeros((batch_size, max_gt), np.int32)
    gt_mask = np.zeros((batch_size, max_gt), bool)
    for b in range(batch_size):
        n = int(rng.integers(4, 24))
        xy = rng.uniform(0, [w - 64, h - 64], (n, 2))
        wh = rng.uniform(16, 256, (n, 2))
        gt_boxes[b, :n, 0::2] = np.stack([xy[:, 0], np.minimum(xy[:, 0] + wh[:, 0], w)], 1)
        gt_boxes[b, :n, 1::2] = np.stack([xy[:, 1], np.minimum(xy[:, 1] + wh[:, 1], h)], 1)
        gt_labels[b, :n] = rng.integers(0, 80, n)
        gt_mask[b, :n] = True
    return {
        # uint8, as the pipeline ships it (normalization runs on device and
        # fuses into the stem; measured ~2% faster than feeding f32).
        "images": jnp.asarray(
            rng.integers(0, 256, (batch_size, h, w, 3), dtype=np.uint8)
        ),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


def run_bench(batch_size: int) -> float:
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import (
        create_train_state,
        make_train_step,
    )

    # frozen_bn is the reference's fine-tune configuration (BN frozen during
    # detection training, SURVEY.md M2) and measures ~9% faster than GN on
    # v5e (pure scale+bias fuses into the convs; GN's per-group moments are
    # extra bandwidth-bound passes).
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", norm_kind="frozen_bn"
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01, momentum=0.9), (1, *BUCKET, 3), jax.random.key(0)
    )
    step = make_train_step(model, BUCKET, 80, donate_state=True)
    batch = make_batch(batch_size, BUCKET)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(float(metrics["loss"]))
    return batch_size * MEASURE_STEPS / dt


def first_recorded_bench() -> float | None:
    vals = {}
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                vals[int(m.group(1))] = float(json.load(f)["value"])
        except Exception:
            continue
    return vals[min(vals)] if vals else None


def main() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    try:
        ips = run_bench(batch_size)
    except Exception as e:
        # Retry smaller only for HBM exhaustion; real bugs propagate.
        oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
        if batch_size <= 2 or not oom:
            raise
        print(f"# batch {batch_size} OOM; retrying at 2", flush=True)
        batch_size = 2
        ips = run_bench(batch_size)

    baseline = first_recorded_bench()
    value = round(ips, 3)
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_per_chip",
                "value": value,
                "unit": "images/sec/chip",
                "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
