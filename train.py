#!/usr/bin/env python
"""RetinaNet-on-COCO training entrypoint — the reference `train.py` surface,
TPU-native underneath.

Reference parity (SURVEY.md W1/M11, §5.6): argparse CLI with a dataset
subcommand (`train.py coco <path>`), flags for batch size / lr / steps /
snapshot path / backbone / freeze-backbone / image sides.  What changed
underneath (BASELINE.json:5): hvd.init → `jax.distributed.initialize`;
`hvd.DistributedOptimizer`'s NCCL allreduce → `lax.pmean` over a `data` mesh
axis inside ONE jit-compiled SPMD step; Keras fit_generator → an explicit
step loop; rank-0 .h5 snapshots → orbax multi-host checkpoints; the CocoEval
callback → an on-device detect + numpy mAP oracle eval hook.

The five BASELINE.json configs are runnable by name via ``--preset``:

  cpu-inference  single-image COCO inference smoke (configs[0])
  coco-mini      single-device overfit training (configs[1])
  dp8            single-host 8-chip data-parallel training (configs[2])
  pod            multi-host pod training, full COCO2017 1333x800 (configs[3])
  eval           on-device batched NMS + mAP@[.5:.95] eval (configs[4])
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

PRESETS: dict[str, dict] = {
    # BASELINE.json configs[0]: single-image CPU-reference inference.
    "cpu-inference": {"eval_only": True, "batch_size": 1, "num_devices": 1},
    # configs[1]: focal+smooth-L1 training on COCO-mini, single device.
    "coco-mini": {
        "batch_size": 2,
        "steps": 500,
        "num_devices": 1,
        "eval_every": 0,
        "schedule": "constant",
    },
    # configs[2]: single-host 8-chip DP (psum gradient allreduce).
    "dp8": {"batch_size": 16, "num_devices": 8},
    # configs[3]: multi-host pod, full COCO2017 at 1333x800 multiscale.
    "pod": {
        "batch_size": 256,
        "num_devices": 0,  # 0 = all global devices
        "distributed_auto": True,
        "steps": 90000 // 16,  # ~12 epochs at global batch 256
    },
    # configs[4]: COCO eval — on-device batched NMS + mAP computation.
    "eval": {"eval_only": True},
}


from batchai_retinanet_horovod_coco_tpu.data.pipeline import (  # noqa: E402
    default_buckets,
)
from batchai_retinanet_horovod_coco_tpu.models.retinanet import (  # noqa: E402
    BACKBONES,
)


# Shared with convert_model.py / debug.py — one anchor surface (utils/cli.py).
from batchai_retinanet_horovod_coco_tpu.utils.cli import (  # noqa: E402
    add_anchor_flags,
    add_comm_flags,
    add_data_pipeline_flags,
    add_durability_flags,
    add_obs_flags,
    configure_obs,
    make_anchor_config,
    make_comm_config,
    make_pipeline_worker_kwargs,
    resolve_anchor_config,
    save_anchor_config,
)


def build_parser() -> argparse.ArgumentParser:
    # allow_abbrev=False: preset-default resolution compares raw argv flag
    # names against dest names, which only works with unabbreviated flags.
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
        allow_abbrev=False,
    )
    p.add_argument("--preset", choices=sorted(PRESETS), default=None,
                   help="named BASELINE.json config; explicit flags override")

    sub = p.add_subparsers(dest="dataset_type", required=True)
    coco = sub.add_parser(
        "coco", help="train on a COCO-format dataset", allow_abbrev=False
    )
    coco.add_argument("coco_path", help="dataset root")
    coco.add_argument("--train-annotations",
                      default="annotations/instances_train2017.json")
    coco.add_argument("--train-images", default="train2017")
    coco.add_argument("--val-annotations",
                      default="annotations/instances_val2017.json")
    coco.add_argument("--val-images", default="val2017")
    csvp = sub.add_parser(
        "csv", help="train on a CSV-format dataset "
        "(keras-retinanet annotations.csv/classes.csv)", allow_abbrev=False,
    )
    csvp.add_argument("csv_annotations", help="annotations CSV "
                      "(path,x1,y1,x2,y2,class_name)")
    csvp.add_argument("csv_classes", help="classes CSV (class_name,id)")
    csvp.add_argument("--val-csv-annotations", default=None,
                      help="validation annotations CSV (default: none)")
    csvp.add_argument("--image-dir", default=None,
                      help="base dir for image paths (default: the "
                           "annotations file's directory)")
    pascal = sub.add_parser(
        "pascal", help="train on a Pascal VOC dataset (VOCdevkit layout)",
        allow_abbrev=False,
    )
    pascal.add_argument("pascal_path", help="VOCdevkit year root "
                        "(contains Annotations/, JPEGImages/, ImageSets/)")
    pascal.add_argument("--train-split", default="trainval")
    pascal.add_argument("--val-split", default="test")
    pascal.add_argument("--skip-difficult", action="store_true",
                        help="drop difficult objects entirely (default: "
                             "keep as ignore regions)")
    synth = sub.add_parser(
        "synthetic", help="generated dataset (air-gapped dev/CI path)",
        allow_abbrev=False,
    )
    synth.add_argument("--synthetic-root", default="/tmp/synthetic_coco")
    synth.add_argument("--synthetic-images", type=int, default=64)
    synth.add_argument("--synthetic-classes", type=int, default=3)
    synth.add_argument("--synthetic-size", default="256",
                       help="source image size: N (square) or HxW — e.g. "
                            "800x1344 generates images that land exactly in "
                            "the flagship bucket (make convergence-full)")

    for sp in (coco, csvp, pascal, synth):
        # Also accepted after the subcommand; SUPPRESS so the subparser
        # doesn't clobber a top-level --preset with its default.
        sp.add_argument("--preset", choices=sorted(PRESETS),
                        default=argparse.SUPPRESS, help=argparse.SUPPRESS)
        g = sp.add_argument_group("model")
        g.add_argument("--backbone", default="resnet50", choices=BACKBONES)
        g.add_argument("--norm", default="gn", choices=["gn", "bn", "frozen_bn"])
        g.add_argument("--stem", default="space_to_depth",
                       choices=["conv", "space_to_depth", "space_to_depth4"],
                       help="stem formulation; space_to_depth is the "
                            "math-identical MLPerF reformulation, ~4%% "
                            "faster on TPU (models/resnet.py)")
        g.add_argument("--pack-width", action="store_true",
                       help="width-packed stage2 (ResNet only): fold W "
                            "pairs into channels so the C=64 stage fills "
                            "the MXU lanes; math-identical, measured "
                            "SLOWER on v5e at the flagship bucket "
                            "(bandwidth-bound stage) — opt-in for "
                            "narrow-channel-bound shapes (models/resnet.py)")
        g.add_argument("--f32", action="store_true",
                       help="compute in float32 (default bfloat16)")
        # Anchor hyperparameters (keras-retinanet --config ini parity,
        # SURVEY.md M5/M11): shared surface, utils/cli.py.
        add_anchor_flags(g)
        g.add_argument("--freeze-backbone", action="store_true")
        g.add_argument("--pretrained-backbone", default=None,
                       help="torch resnet50 state dict (.pth/.npz) to import; "
                            "use with --norm frozen_bn (the reference recipe)")

        g = sp.add_argument_group("data")
        g.add_argument("--batch-size", type=int, default=16,
                       help="GLOBAL batch size (split over devices)")
        g.add_argument("--image-min-side", type=int, default=800)
        g.add_argument("--image-max-side", type=int, default=1333)
        g.add_argument("--max-gt", type=int, default=None,
                       help="gt boxes padded per image; default auto-sizes "
                            "to the dataset's true per-image max (COCO "
                            "images can exceed 100) so no box is dropped")
        # --workers / --data-workers / --data-worker-procs /
        # --data-worker-timeout / --device-prefetch (utils/cli.py — shared
        # surface; TPU-VM hosts have ~112 vCPUs and need ~1 core per
        # 3 imgs/s of step demand).
        add_data_pipeline_flags(g)
        g.add_argument("--random-transform", action="store_true",
                       help="full random affine + photometric augmentation "
                            "(reference --random-transform; default is "
                            "hflip-only)")

        g = sp.add_argument_group("optimization")
        g.add_argument("--steps", type=int, default=90000)
        g.add_argument("--lr", type=float, default=0.01)
        g.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
        g.add_argument("--schedule", default="multistep",
                       choices=["multistep", "cosine", "constant", "plateau"])
        g.add_argument("--plateau-factor", type=float, default=0.1,
                       help="LR multiplier on plateau (--schedule plateau)")
        g.add_argument("--plateau-patience", type=int, default=2,
                       help="non-improving windows before reducing")
        g.add_argument("--plateau-window", type=int, default=1000,
                       help="steps of loss averaged per window (epoch analogue)")
        g.add_argument("--plateau-min-delta", type=float, default=1e-4,
                       help="absolute loss improvement below which a window "
                            "counts as a plateau")
        g.add_argument("--warmup-steps", type=int, default=500)
        g.add_argument("--weight-decay", type=float, default=1e-4)
        g.add_argument("--seed", type=int, default=0)

        g = sp.add_argument_group("loop / io")
        g.add_argument("--snapshot-path", default=None,
                       help="checkpoint directory (enables checkpointing)")
        g.add_argument("--checkpoint-every", type=int, default=1000)
        g.add_argument("--no-resume", action="store_true")
        # --resume-elastic / --auto-resume / --max-auto-resumes /
        # --inject-nan-step: preemption & recovery surface (ISSUE 11,
        # utils/cli.py — shared with scripts/chaos.py).
        add_durability_flags(g)
        g.add_argument("--eval-every", type=int, default=0)
        g.add_argument("--async-eval", action="store_true",
                       help="run the mid-training eval hook in a background "
                            "thread on a snapshotted param copy instead of "
                            "blocking the step stream (single-process only; "
                            "multi-host falls back to synchronous — "
                            "train/loop.py::_AsyncEvalRunner)")
        g.add_argument("--log-every", type=int, default=20)
        g.add_argument("--log-dir", default=None)
        g.add_argument("--tensorboard", action="store_true")
        g.add_argument("--profile-dir", default=None,
                       help="write a jax.profiler trace of a few steps here")
        # --obs-trace / --obs-dir / --obs-stall-timeout: structured trace
        # spans + stall watchdog across train/data/eval (utils/cli.py —
        # shared surface, obs/ subsystem).
        add_obs_flags(g)
        g.add_argument("--debug-nans", action="store_true",
                       help="numerical sanitizer (SURVEY.md 5.2): enable "
                            "jax_debug_nans so the originating op of a "
                            "NaN/Inf is reported; the loop independently "
                            "aborts on a non-finite loss either way")
        g.add_argument("--eval-only", action="store_true")
        g.add_argument("--score-threshold", type=float, default=0.05)
        g.add_argument("--nms-threshold", type=float, default=0.5)
        g.add_argument("--max-detections", type=int, default=300)
        g.add_argument("--weighted-average", action="store_true",
                       help="weight the VOC mAP by per-class annotation "
                            "counts (reference Evaluate flag; csv/pascal)")

        g = sp.add_argument_group("distributed")
        g.add_argument("--num-devices", type=int, default=1,
                       help="devices in the data mesh; 0 = all global devices")
        g.add_argument("--platform", default="auto",
                       choices=["auto", "cpu", "tpu"],
                       help="cpu: run the full SPMD path on a virtual CPU "
                            "mesh of --num-devices (CI / laptops, "
                            "SURVEY.md §7.3); auto: default backend")
        g.add_argument("--shard-weight-update", action="store_true",
                       help="ZeRO-style weight-update sharding: "
                            "reduce-scatter grads, 1/N optimizer state per "
                            "device, all_gather params (SURVEY.md §2.4)")
        g.add_argument("--quantized-allreduce", action="store_true",
                       help="DEPRECATED alias for --comm-compress int8 "
                            "(ISSUE 13: the per-leaf quantized allreduce "
                            "was subsumed by the bucketed, error-feedback "
                            "comm/ subsystem); emits one structured "
                            "deprecation warning")
        # --comm-compress / --comm-overlap / --comm-bucket-mb /
        # --comm-no-error-feedback: the gradient-communication policy
        # surface (ISSUE 13, utils/cli.py — shared with chaos/COMMBENCH).
        add_comm_flags(g)
        g.add_argument("--spatial-shards", type=int, default=1,
                       help="shard every image's H axis over this many "
                            "chips on a 2-D data x space mesh (GSPMD conv "
                            "halo exchanges — the sequence/context-parallel "
                            "analogue, SURVEY.md §5.7); must divide "
                            "--num-devices; exclusive with "
                            "--shard-weight-update/--comm-compress")
        g.add_argument("--allow-data-axis-divergence", action="store_true",
                       help="accept the measured gradient divergence of "
                            "deep-backbone spatial training on meshes "
                            "with a data axis >= 2 (round-5 finding; see "
                            "make_train_step_spatial's 'Data-axis "
                            "envelope' docstring)")
        g.add_argument("--distributed-auto", action="store_true",
                       help="jax.distributed.initialize() from TPU metadata")
        g.add_argument("--coordinator-address", default=None)
        g.add_argument("--num-processes", type=int, default=None)
        g.add_argument("--process-id", type=int, default=None)
    return p


def parse_args(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.preset:
        explicit = {
            a[2:].replace("-", "_").split("=")[0]
            for a in (argv if argv is not None else sys.argv[1:])
            if a.startswith("--")
        }
        for k, v in PRESETS[args.preset].items():
            if k not in explicit and hasattr(args, k):
                setattr(args, k, v)
    return args


def make_datasets(args):
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        CsvDataset,
        make_synthetic_coco,
    )

    if args.dataset_type == "csv":
        # keep_empty: explicit 'path,,,,,' rows are intentional negative
        # (background-only) images; the reference CSVGenerator trains on them.
        train = CsvDataset(
            args.csv_annotations, args.csv_classes, image_dir=args.image_dir,
            keep_empty=True,
        )
        val = None
        if args.val_csv_annotations:
            val = CsvDataset(
                args.val_csv_annotations, args.csv_classes,
                image_dir=args.image_dir, keep_empty=True,
            )
        return train, val

    if args.dataset_type == "pascal":
        from batchai_retinanet_horovod_coco_tpu.data import PascalVocDataset

        # keep_empty: the reference PascalVocGenerator keeps every id in the
        # split file, background-only and difficult-only images included.
        train = PascalVocDataset(
            args.pascal_path, split=args.train_split,
            skip_difficult=args.skip_difficult, keep_empty=True,
        )
        val = PascalVocDataset(
            args.pascal_path, split=args.val_split,
            skip_difficult=args.skip_difficult, keep_empty=True,
        )
        return train, val

    if args.dataset_type == "synthetic":
        raw = str(args.synthetic_size)
        if "x" in raw:
            h, w = raw.split("x", 1)
            size = (int(h), int(w))
        else:
            size = (int(raw), int(raw))
        train_ann = make_synthetic_coco(
            args.synthetic_root, num_images=args.synthetic_images,
            num_classes=args.synthetic_classes, image_size=size,
            seed=args.seed, split="train",
        )
        val_ann = make_synthetic_coco(
            args.synthetic_root, num_images=max(8, args.synthetic_images // 4),
            num_classes=args.synthetic_classes, image_size=size,
            seed=args.seed + 1, split="val",
        )
        train = CocoDataset(train_ann, os.path.join(args.synthetic_root, "train"))
        val = CocoDataset(
            val_ann, os.path.join(args.synthetic_root, "val"), keep_empty=True
        )
        return train, val

    root = args.coco_path
    train = CocoDataset(
        os.path.join(root, args.train_annotations),
        os.path.join(root, args.train_images),
    )
    val = CocoDataset(
        os.path.join(root, args.val_annotations),
        os.path.join(root, args.val_images),
        keep_empty=True,
    )
    return train, val


def main(argv=None) -> dict[str, float]:
    args = parse_args(argv)
    # Observability bring-up precedes everything that spawns threads or
    # worker processes: the shm decode workers inherit the trace env
    # contract at spawn, so tracing must be configured before any
    # pipeline is built.  The finalize runs even when the run dies — the
    # partial trace (+ the watchdog's stall dump) IS the post-mortem.
    obs_dir = configure_obs(args, process_label="train")
    if obs_dir is None:
        return _run(args)
    if not args.log_dir:
        # The perf doctor (obs/analyze) reads the run's events JSONL next
        # to its trace: an obs-enabled run without an explicit --log-dir
        # logs into the obs dir so the report never lacks its events half.
        args.log_dir = obs_dir
    try:
        return _run(args)
    finally:
        from batchai_retinanet_horovod_coco_tpu import obs

        merged = obs.finalize()
        if merged:
            print(f"obs: merged Chrome trace at {merged} "
                  "(load in Perfetto / chrome://tracing)", flush=True)
            # Auto-emit PERF_REPORT.json next to the trace.  Analysis can
            # never crash the run: auto_emit swallows its own failures
            # into ONE structured perf_report_error line, and the import
            # is guarded for the same reason.
            try:
                from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
                    auto_emit,
                )

                report = auto_emit(obs_dir)
            except Exception as e:  # never mask the run's own outcome
                import json as _json

                print(
                    _json.dumps(
                        {"event": "perf_report_error", "error": repr(e)[:500]}
                    ),
                    file=sys.stderr,
                    flush=True,
                )
                report = None
            if report:
                print(
                    f"obs: perf report at {report} (reproduce offline: "
                    "python -m batchai_retinanet_horovod_coco_tpu.obs."
                    f"analyze {obs_dir})",
                    flush=True,
                )


def _start_telemetry(args, logger):
    """Live-telemetry bring-up (ISSUE 9): the --obs-port status server
    (GET /metrics /healthz /statusz over the process-default registry the
    train loop feeds) and the SLO monitor (--slo-rule + the built-in
    watchdog-stall rule), violations sinking into the run's metrics
    JSONL.  Returns (status_server | None, slo_monitor | None); the
    caller owns the bounded, idempotent teardown — both are daemon-
    threaded and can never wedge a pod exit."""
    port = getattr(args, "obs_port", None)
    rule_specs = getattr(args, "slo_rule", None) or []
    if port is None and not rule_specs:
        return None, None
    from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry

    telemetry.enable()  # arm the loop's push record sites (one bool)
    server = None
    if port is not None:
        server = telemetry.start_http_server(telemetry.default(), port=port)
        print(
            f"obs: telemetry on http://{server.host}:{server.port} "
            "(/metrics /healthz /statusz)",
            flush=True,
        )
    monitor = slo.SloMonitor(
        telemetry.default(),
        # Built-ins first: the watchdog-stall rule, the immediate
        # nonfinite rule (ISSUE 10 — fed by the loop's abort path and
        # the in-step numerics summary), and the grad-norm-spike
        # regression rule (rolling-median baseline; silent until the
        # train_grad_norm gauge exists, so serve/eval runs are
        # unaffected).  User --slo-rule specs append after.
        [slo.stall_rule(), slo.nonfinite_rule(), slo.grad_norm_spike(),
         # Checkpoint staleness (ISSUE 11): silent until two saves have
         # landed (the age/interval gauge needs a measured cadence), so
         # un-checkpointed runs never see it evaluate.
         slo.ckpt_staleness_rule(),
         # Gradient-compression EF health (ISSUE 13): always armed —
         # silent until the train_ef_residual gauge exists, i.e. on
         # every run without --comm-compress.
         slo.ef_residual_spike(),
         # Per-hop variant (ISSUE 16): the DCN hop is the only one
         # that quantizes under a hierarchical topology; silent until
         # the train_ef_residual_dcn gauge exists (flat runs never
         # create it).
         slo.ef_residual_spike(hop="dcn")]
        + [slo.parse_rule(s) for s in rule_specs],
        sink=logger,
        poll_interval=getattr(args, "slo_poll_s", 5.0),
    ).start()
    return server, monitor


def _elastic_skip_batches(args) -> dict:
    """--resume-elastic: the stream plan that continues exactly where the
    checkpointed run stopped — ``{"skip", "data_seed", "exclude_ids"}``.

    The loop consumes ONE batch per process per step at every world size
    (the global batch is split over processes), so the position within a
    stream is ``step - stream_base_step`` (base 0 for a virgin run; an
    --auto-resume heal RESTARTS the stream at its restore step with a new
    seed and exclusions, and records all three in the manifest so this
    derivation survives the heal).  The global batch size must match the
    manifest (validated; a change makes the position meaningless, so it
    aborts loudly), and so must --seed for a virgin stream; for a healed
    stream the manifest's effective seed/exclusions WIN — they are the
    order that was actually consumed.  At the same world size the
    continuation is sample-exact (chaos-pinned bit-identical losses);
    across a world-size change the per-shard record partition differs, so
    it is position-exact and distribution-equivalent (PARITY.md).
    """
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        read_manifest,
    )

    plan = {
        "skip": 0,
        "data_seed": int(args.seed),
        "exclude_ids": (),
        "stream_base_step": 0,
    }
    manifest = read_manifest(args.snapshot_path)
    if manifest is None:
        return plan
    meta = manifest.get("metadata") or {}
    base = int(meta.get("stream_base_step") or 0)
    saved_gb = meta.get("global_batch_size")
    if saved_gb is not None and int(saved_gb) != int(args.batch_size):
        raise SystemExit(
            f"--resume-elastic: global_batch_size changed since the "
            f"checkpoint was written ({saved_gb} -> {args.batch_size}); "
            "the stream position is only re-derivable at the batch size "
            "the manifest recorded.  Re-run with the original value, or "
            "drop --resume-elastic to resume with a restarted stream."
        )
    saved_seed = meta.get("data_seed")
    if base == 0 and saved_seed is not None and int(saved_seed) != int(
        args.seed
    ):
        raise SystemExit(
            f"--resume-elastic: data_seed changed since the checkpoint "
            f"was written ({saved_seed} -> {args.seed}); the stream "
            "position is only re-derivable with the data order the "
            "manifest recorded.  Re-run with the original value, or drop "
            "--resume-elastic to resume with a restarted stream."
        )
    if saved_seed is not None:
        plan["data_seed"] = int(saved_seed)  # healed stream: manifest wins
    plan["exclude_ids"] = tuple(
        int(i) for i in (meta.get("exclude_ids") or [])
    )
    plan["stream_base_step"] = base
    plan["skip"] = max(0, int(manifest.get("step") or 0) - base)
    if plan["skip"] or base:
        print(
            json.dumps(
                {
                    "event": "elastic_resume",
                    "restored_step": int(manifest.get("step") or 0),
                    "skip_batches_per_process": plan["skip"],
                    "stream_base_step": base,
                    "data_seed": plan["data_seed"],
                    "excluded": len(plan["exclude_ids"]),
                    "saved_world": meta.get("shard_count"),
                    "zero_world_size": manifest.get("zero_world_size"),
                }
            ),
            flush=True,
        )
    return plan


def _read_poison_ids(dump_dir: str | None) -> list[int]:
    """The tripped batch's source image ids from NUMERICS_DUMP.json (the
    numerics abort wrote it just before raising); [] when unavailable."""
    if not dump_dir:
        return []
    path = os.path.join(dump_dir, "NUMERICS_DUMP.json")
    try:
        with open(path) as f:
            dump = json.load(f)
    except (OSError, json.JSONDecodeError):
        return []
    try:
        return [int(i) for i in (dump.get("batch_image_ids") or [])]
    except (TypeError, ValueError):
        return []


def _auto_resume_plan(args, attempt: int, exc: BaseException) -> dict | None:
    """Decide whether a numerics abort is self-healable (--auto-resume)
    and with what; None = re-raise.  Requires a restorable checkpoint
    (guaranteed finite by the loop's pre-save gate) and a remaining
    attempt budget; the plan reseeds the data order and carries the
    poison batch's image ids for exclusion."""
    if not getattr(args, "auto_resume", False):
        return None
    if attempt > getattr(args, "max_auto_resumes", 3):
        return None
    if not args.snapshot_path or args.no_resume:
        return None
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        latest_step as ckpt_latest_step,
    )

    restored = ckpt_latest_step(args.snapshot_path)
    if restored is None:
        return None  # nothing healthy on disk — the abort stands
    dump_dir = getattr(args, "obs_dir", None) or args.log_dir
    return {
        "attempt": attempt,
        "restored_step": int(restored),
        # A deterministic reseed: the new (seed, epoch) permutation makes
        # the post-resume order disjoint from the aborted one, and the
        # exclusion below guarantees the poison batch cannot recur even
        # if an image repeats.
        "data_seed": int(args.seed) + 7919 * attempt,
        "exclude_ids": _read_poison_ids(dump_dir),
        "error": str(exc)[:300],
    }


class _NanInjector:
    """--inject-nan-step fault hook (scripts/chaos.py): poison the N-th
    consumed batch, exactly once per PROCESS — ``latch`` is shared across
    auto-resume attempts so the fault cannot re-fire on the healed
    stream.  The NaN goes into the IMAGE tensor (the uint8 production
    batch is lifted to float32 first — normalize_images passes float
    through — because uint8 cannot carry a NaN, and poisoning gt boxes
    does NOT trip the sanitizer: NaN IoU comparisons are all False, so
    matching classifies the poisoned anchors as 'ignore' and the NaN
    never reaches the loss)."""

    def __init__(self, inner, at_batch: int, latch: dict):
        self._inner = inner
        self._at = int(at_batch)
        self._latch = latch
        self._count = 0

    @property
    def stats(self):
        return getattr(self._inner, "stats", None)

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._inner)
        self._count += 1
        if not self._latch["done"] and self._count == self._at:
            self._latch["done"] = True
            images = batch.images.astype(np.float32, copy=True)
            images[0, 0, 0, 0] = np.nan
            batch = batch._replace(images=images)
            print(
                json.dumps(
                    {
                        "event": "chaos_nan_injected",
                        "batch": self._count,
                        "image_ids": [int(i) for i in batch.image_ids],
                    }
                ),
                file=sys.stderr, flush=True,
            )
        return batch


def _run(args) -> dict[str, float]:
    if args.platform != "auto":
        # Must land before any backend initialization.  The CPU path also
        # forces enough virtual host devices for the requested mesh
        # (xla_force_host_platform_device_count is read at backend init).
        if args.platform == "cpu" and args.num_devices > 1:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{args.num_devices}"
                ).strip()
        jax.config.update("jax_platforms", args.platform)

    if args.debug_nans:
        # SURVEY.md §5.2 numerical sanitizer: every jit result is checked
        # and the failing op re-run un-jitted for a precise report.
        jax.config.update("jax_debug_nans", True)

    from batchai_retinanet_horovod_coco_tpu.data import (
        PipelineConfig,
        build_pipeline,
        resolve_max_gt,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        run_coco_eval,
    )
    from batchai_retinanet_horovod_coco_tpu.launch import (
        DistributedConfig,
        initialize_distributed,
        shard_info,
    )
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import (
        derive_topology,
        make_mesh,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.train.loop import LoopConfig, run_training
    from batchai_retinanet_horovod_coco_tpu.train.optim import (
        OptimizerConfig,
        make_optimizer,
    )
    from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger

    initialize_distributed(
        DistributedConfig(
            auto=args.distributed_auto,
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    )
    num_devices = args.num_devices or len(jax.devices())
    spatial_shards = int(getattr(args, "spatial_shards", 1) or 1)
    if spatial_shards > 1:
        if num_devices % spatial_shards:
            raise SystemExit(
                f"--spatial-shards {spatial_shards} must divide "
                f"--num-devices {num_devices}"
            )
        if (
            getattr(args, "shard_weight_update", False)
            or getattr(args, "quantized_allreduce", False)
            or getattr(args, "comm_compress", "none") != "none"
            or getattr(args, "comm_overlap", False)
        ):
            raise SystemExit(
                "--spatial-shards is exclusive with --shard-weight-update "
                "and --comm-compress/--comm-overlap/--quantized-allreduce"
            )
        if not args.f32:
            # The SPMD partitioner miscompiles the bf16 spatial train step
            # at flagship width (wrong cls_loss, 14-60x wrong grads;
            # train/step.py::make_train_step_spatial docstring + the bf16
            # spatial canary test).  Refuse loudly rather than train on
            # silently corrupted gradients.
            raise SystemExit(
                "--spatial-shards requires --f32: bf16 spatial train "
                "steps are miscompiled by XLA's SPMD partitioner "
                "(validated on the CPU mesh rig; TPU unvalidated — see "
                "make_train_step_spatial's docstring)"
            )
        # Fail fast on the strided-conv sharding envelope for EVERY bucket
        # this run will compile, instead of letting make_train_step_spatial
        # raise mid-training when the offending bucket first arrives.
        # (default_buckets is the module-level import — a function-local
        # re-import here would shadow it for the whole function and break
        # every non-spatial run with UnboundLocalError.)
        from batchai_retinanet_horovod_coco_tpu.train.step import (
            _degenerate_strided_conv_heights,
        )

        bad = {
            f"{h}x{w}": _degenerate_strided_conv_heights(h, spatial_shards)
            for h, w in default_buckets(
                args.image_min_side, args.image_max_side
            )
            if _degenerate_strided_conv_heights(h, spatial_shards)
        }
        if bad:
            raise SystemExit(
                f"--spatial-shards {spatial_shards} puts bucket(s) "
                f"{sorted(bad)} inside the XLA strided-conv weight-grad "
                "bug envelope (conv input heights "
                f"{sorted(set(sum(bad.values(), [])))} at ~[0.5, 2) rows "
                "per shard; see make_train_step_spatial).  Use "
                "--spatial-shards 4 or fewer, which is always outside "
                "the envelope"
            )
        if (
            jax.process_count() > 1
            and len(jax.local_devices()) % spatial_shards
        ):
            # The space axis must stay within one host: the per-process
            # batch assembly hands each process its own full-H images, so a
            # space row straddling hosts would silently stitch H-slices of
            # DIFFERENT hosts' images into one "global" image.
            raise SystemExit(
                f"--spatial-shards {spatial_shards} must divide the "
                f"per-host device count {len(jax.local_devices())} on "
                "multi-host runs (the space axis cannot span hosts)"
            )
        from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
            make_mesh_2d,
        )
        from batchai_retinanet_horovod_coco_tpu.train.step import (
            _SPATIAL_GRAD_VALIDATED_BACKBONES,
            _data_axis_risky_stage_heights,
        )

        data_size = num_devices // spatial_shards
        risky_buckets = {
            f"{h}x{w}": _data_axis_risky_stage_heights(h, spatial_shards)
            for h, w in default_buckets(
                args.image_min_side, args.image_max_side
            )
            if _data_axis_risky_stage_heights(h, spatial_shards)
        }
        if (
            data_size > 1
            and risky_buckets
            and args.backbone not in _SPATIAL_GRAD_VALIDATED_BACKBONES
            and not args.allow_data_axis_divergence
        ):
            # Round-5 finding: deep-backbone spatial training on meshes
            # with data >= 2 computes measurably wrong gradients when a
            # backbone stage lands at <= 1 row per shard (f64-
            # persistent, ~3x worse per data doubling).  Fail fast here
            # with the same policy make_train_step_spatial enforces.
            raise SystemExit(
                f"--spatial-shards {spatial_shards} on {num_devices} "
                f"devices gives a (data={data_size}, space="
                f"{spatial_shards}) mesh, and bucket(s) "
                f"{sorted(risky_buckets)} put backbone-stage maps at "
                "<= 1 row per shard, where deep-backbone spatial "
                "training with a data axis >= 2 computes measurably "
                "divergent gradients (see make_train_step_spatial's "
                "'Data-axis envelope').  Use --num-devices == "
                "--spatial-shards for the pure-spatial mode, larger "
                "--image-min/max-side, plain DP, or pass "
                "--allow-data-axis-divergence to accept the measured "
                "error"
            )
        mesh = make_mesh_2d(data_size, spatial_shards)
        comm_topology = None  # spatial mesh: no hierarchical comm path
    else:
        data_size = num_devices
        # Two-level comm topology (ISSUE 16): --comm-slices / the env
        # override / real per-device slice indices resolve to slice ×
        # intra-slice grouping; None on flat (single-slice) machines.
        # Derived BEFORE the mesh so device order interleaves slices
        # (mesh position d on slice d % S) — the invariant that keeps
        # hierarchical EF residuals in global bucket order for
        # checkpoint resharding.
        comm_topology = (
            derive_topology(num_devices, getattr(args, "comm_slices", None))
            if num_devices > 1
            else None
        )
        mesh = (
            make_mesh(num_devices, topology=comm_topology)
            if num_devices > 1
            else None
        )
    if args.batch_size % data_size:
        raise SystemExit(
            f"--batch-size {args.batch_size} not divisible by the data-mesh "
            f"size {data_size}"
        )

    train_ds, val_ds = make_datasets(args)
    num_classes = train_ds.num_classes
    # Auto-size gt padding to the data (silent truncation poisons targets);
    # an explicit --max-gt is honored and the pipeline counts what it drops.
    args.max_gt = resolve_max_gt(
        args.max_gt, *(ds for ds in (train_ds, val_ds) if ds is not None)
    )
    if val_ds is None and (args.eval_only or args.eval_every):
        raise SystemExit(
            "no validation set: pass --val-csv-annotations to evaluate"
        )

    # Flags + the config persisted beside the checkpoint (conflict = abort);
    # persist on fresh training so eval/export/resume never need the flags.
    anchor_config = resolve_anchor_config(
        args, args.snapshot_path, fresh=args.no_resume
    )
    if args.snapshot_path and not args.eval_only and jax.process_index() == 0:
        save_anchor_config(args.snapshot_path, anchor_config)
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=num_classes,
            backbone=args.backbone,
            norm_kind=args.norm,
            stem=args.stem,
            pack_width=getattr(args, "pack_width", False),
            anchor=anchor_config,
            dtype=jnp.float32 if args.f32 else jnp.bfloat16,
        )
    )
    opt_config = OptimizerConfig(
        optimizer=args.optimizer,
        base_lr=args.lr,
        global_batch_size=args.batch_size,
        world_size=jax.process_count(),
        warmup_steps=args.warmup_steps,
        total_steps=args.steps,
        schedule=args.schedule,
        plateau_factor=args.plateau_factor,
        plateau_patience=args.plateau_patience,
        plateau_window=args.plateau_window,
        plateau_min_delta=args.plateau_min_delta,
        weight_decay=args.weight_decay,
        freeze_backbone=args.freeze_backbone,
    )
    shard_update = bool(getattr(args, "shard_weight_update", False))
    if shard_update and num_devices <= 1:
        raise SystemExit("--shard-weight-update needs --num-devices > 1")
    # Gradient-communication policy (ISSUE 13): flags (+ the deprecated
    # --quantized-allreduce alias) resolve to ONE CommConfig; composes
    # with --shard-weight-update (compressed ZeRO update gather — the
    # old exclusivity is lifted).
    comm_cfg = make_comm_config(args)
    if comm_cfg is not None and num_devices <= 1:
        raise SystemExit(
            "--comm-compress/--comm-overlap need --num-devices > 1 "
            "(compression rides the mesh collectives)"
        )
    if comm_cfg is not None and comm_cfg.overlap and shard_update:
        # ZeRO compresses the POST-update gather; there is no backward
        # gradient collective for overlap to restage.  One structured
        # line, then drop the flag (never a silent no-op).
        print(
            json.dumps({
                "event": "comm_overlap_ignored",
                "reason": (
                    "--comm-overlap is a DP-path mechanism; "
                    "--shard-weight-update compresses the post-update "
                    "gather instead"
                ),
            }),
            file=sys.stderr, flush=True,
        )
        comm_cfg = dataclasses.replace(comm_cfg, overlap=False)
    # Sharded-update mode swaps in the cross-shard global-norm clip — same
    # chain position, same clip value, one source of truth (parallel/zero.py).
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS

    tx, schedule = make_optimizer(
        opt_config, shard_clip_axis=DATA_AXIS if shard_update else None
    )
    buckets = default_buckets(args.image_min_side, args.image_max_side)
    init_hw = buckets[0]

    def build_state():
        """Fresh TrainState from the run's flags — called once at startup
        and again per --auto-resume attempt (the poisoned state was
        donated into the aborted step; the loop's resume then restores
        the last healthy checkpoint into this template)."""
        state = create_train_state(
            model, tx, (1, *init_hw, 3), jax.random.key(args.seed),
            init_opt_state=not shard_update,
        )
        if shard_update:
            from batchai_retinanet_horovod_coco_tpu.parallel import (
                init_sharded_opt_state,
                replicated_sharding,
            )

            # Replicate params over the GLOBAL mesh first: on multi-host
            # runs they come out of init committed to the local default
            # device, which a shard_map over a cross-process mesh cannot
            # reshard implicitly.
            params = jax.device_put(state.params, replicated_sharding(mesh))
            state = state.replace(
                params=params,
                opt_state=init_sharded_opt_state(tx, params, mesh),
            )
        if comm_cfg is not None and comm_cfg.needs_state and mesh is not None:
            # Zeroed EF residuals in the layout the step expects (per
            # bucket for DP, per leaf for ZeRO); host numpy — the loop's
            # replication block places them data-axis-sharded, and a
            # checkpoint restore reshards into these shapes.
            from batchai_retinanet_horovod_coco_tpu.comm import (
                init_comm_state,
            )

            state = state.replace(
                comm_state=init_comm_state(
                    state.params, comm_cfg, mesh.size, zero=shard_update,
                    topology=comm_topology,
                )
            )
        if args.pretrained_backbone:
            from batchai_retinanet_horovod_coco_tpu.models.import_weights import (
                apply_backbone_weights,
                convert_torch_resnet50,
                load_state_dict,
            )

            imp_params, imp_stats = convert_torch_resnet50(
                load_state_dict(args.pretrained_backbone)
            )
            new_params, new_stats = apply_backbone_weights(
                state.params, state.batch_stats, imp_params, imp_stats
            )
            state = state.replace(params=new_params, batch_stats=new_stats)
            print(f"imported backbone weights from {args.pretrained_backbone}")
        return state

    state = build_state()

    shard_index, shard_count = shard_info()
    if args.batch_size % shard_count:
        raise SystemExit(
            f"--batch-size {args.batch_size} not divisible by "
            f"{shard_count} host processes"
        )
    local_batch = args.batch_size // shard_count
    pipe_common = dict(
        buckets=buckets,
        min_side=args.image_min_side,
        max_side=args.image_max_side,
        max_gt=args.max_gt,
        seed=args.seed,
        # --workers / --data-worker-procs / --data-worker-timeout: the
        # multiprocess shared-memory producer when procs > 0 (RUNBOOK.md
        # "Feeding the chips"), the thread pool otherwise.
        **make_pipeline_worker_kwargs(args),
    )
    train_transform = None
    if getattr(args, "random_transform", False):
        from batchai_retinanet_horovod_coco_tpu.data import TransformConfig

        train_transform = TransformConfig()
    detect_config = DetectConfig(
        score_threshold=args.score_threshold,
        iou_threshold=args.nms_threshold,
        max_detections=args.max_detections,
        anchor=anchor_config,
    )

    def eval_fn(eval_state) -> dict[str, float]:
        # Val work is SHARDED across processes: each host decodes its slice
        # of the val set and detects on its LOCAL devices; the detections
        # all-gather before scoring (evaluate/detect.py).  The reference ran
        # the whole eval on rank 0 (SURVEY.md M10) — at pod scale that is
        # hosts× redundant decode; here host work scales 1/process_count.
        # Only process 0 logs the (identical, post-gather) metrics.
        if shard_count > 1:
            from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
                make_local_mesh,
            )

            eval_mesh = make_local_mesh()
            eval_batch = max(
                len(jax.local_devices()),
                args.batch_size // shard_count,
            )
            # The training state is replicated over the GLOBAL mesh; a
            # local-mesh program cannot consume it directly.  Replicated →
            # every shard is addressable → one host copy suffices; re-upload
            # it ONCE onto the local mesh (process-local put) so the detect
            # fn is not fed numpy — that would re-transfer ~450 MB of
            # params+optimizer state per eval batch.
            from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
                replicated_sharding,
            )

            # Detection needs only params/batch_stats/step.  Drop opt_state
            # BEFORE the host round-trip: (a) under --shard-weight-update the
            # optimizer slots are sharded P(DATA_AXIS) over the global mesh,
            # so their shards are non-addressable from one host and
            # device_get would raise; (b) even replicated, it halves the
            # per-eval host<->device traffic (optimizer slots ~= params).
            # comm_state (EF residuals) drops with it: detection never
            # reads it, and under compression its leaves are data-axis-
            # sharded over the GLOBAL mesh (non-addressable cross-host).
            eval_state = eval_state.replace(opt_state=(), comm_state=())
            eval_state = jax.device_put(
                jax.device_get(eval_state), replicated_sharding(eval_mesh)
            )
        else:
            eval_mesh = mesh
            eval_batch = args.batch_size
            from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
                SPACE_AXIS,
            )

            if mesh is not None and SPACE_AXIS in mesh.axis_names:
                # Eval is batch-parallel: flatten the 2-D train mesh so the
                # space-axis chips do real work instead of replaying the
                # data rows' detection pass (detect shards over `data`
                # only).  Round the eval batch up to the flat mesh size.
                from jax.sharding import Mesh as _Mesh

                from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
                    DATA_AXIS,
                    replicated_sharding,
                )

                eval_mesh = _Mesh(
                    mesh.devices.reshape(-1), axis_names=(DATA_AXIS,)
                )
                n = eval_mesh.size
                eval_batch = ((args.batch_size + n - 1) // n) * n
                eval_state = eval_state.replace(opt_state=(), comm_state=())
                eval_state = jax.device_put(
                    eval_state, replicated_sharding(eval_mesh)
                )
        val_batches = build_pipeline(
            val_ds,
            PipelineConfig(
                batch_size=eval_batch, shuffle=False, hflip_prob=0.0,
                shard_index=shard_index, shard_count=shard_count,
                **pipe_common,
            ),
            train=False,
        )
        return run_coco_eval(
            eval_state, model, val_ds, val_batches, detect_config,
            mesh=eval_mesh,
            # CSV/Pascal datasets additionally report the reference's
            # Evaluate-callback metric (VOC AP@0.5 per class) from the same
            # detection pass.
            voc_metrics=args.dataset_type in ("csv", "pascal"),
            voc_weighted_average=args.weighted_average,
        )

    # run_config feeds the JSONL run-header's config digest: two runs in
    # one log dir are the same experiment iff their digests match.
    logger = MetricLogger(
        args.log_dir, tensorboard=args.tensorboard, run_config=vars(args)
    )
    if getattr(args, "obs_trace", False) or getattr(args, "obs_dir", None):
        # The sink outlives every watchdog poll (closed only at process
        # end), so stall diagnoses land in metrics.jsonl next to the
        # metrics they interrupt — configure_obs ran before the logger
        # existed, so the attachment happens here.
        from batchai_retinanet_horovod_coco_tpu.obs import watchdog

        watchdog.default().sink = logger

    # Live telemetry around the run (status server + SLO monitor); the
    # teardown is bounded and idempotent, so a traced run's obs finalize
    # (main()'s finally) always runs after a clean telemetry drain.
    telem_server, slo_monitor = _start_telemetry(args, logger)
    try:
        if args.eval_only:
            if args.snapshot_path:
                from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
                    CheckpointManager,
                )

                state = CheckpointManager(args.snapshot_path).restore(state)
                if mesh is None:
                    # Restore returns HOST numpy; put once so the detect
                    # programs don't re-transfer params on every dispatch
                    # (read-only use — no donation — so a plain put is
                    # safe here, unlike the training path).
                    state = jax.device_put(state)
            if mesh is not None and shard_count == 1:
                # Multi-host skips this: restored arrays are committed to
                # local devices (cross-host device_put is unsupported on
                # some backends) and the sharded eval_fn pulls state to
                # host anyway.
                from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
                    replicated_sharding,
                )

                state = jax.device_put(state, replicated_sharding(mesh))
            metrics = eval_fn(state)
            logger.log(int(state.step), metrics, prefix="eval")
            return metrics

        # Durability surface (ISSUE 11).  The manifest records the
        # data-order facts; --resume-elastic re-derives the stream
        # position (consumed batches per process == restored step, at any
        # world size — the global batch is validated unchanged).
        numerics_dump_dir = (
            getattr(args, "obs_dir", None) or args.log_dir or None
        )
        # MUTATED in place on --auto-resume: run_training builds a fresh
        # CheckpointManager (which copies this dict) per attempt, so
        # post-heal checkpoints record the EFFECTIVE stream facts — seed,
        # exclusions, and the step the reseeded stream restarted at —
        # which _elastic_skip_batches trusts over the CLI flags.
        ckpt_metadata = {
            "global_batch_size": args.batch_size,
            "data_seed": args.seed,
            "shard_count": shard_count,
            "stream_base_step": 0,
            "exclude_ids": [],
        }
        skip_batches = 0
        data_seed = args.seed
        exclude_ids: tuple[int, ...] = ()
        if (
            getattr(args, "resume_elastic", False)
            and args.snapshot_path
            and not args.no_resume
        ):
            stream_plan = _elastic_skip_batches(args)
            skip_batches = stream_plan["skip"]
            data_seed = stream_plan["data_seed"]
            exclude_ids = stream_plan["exclude_ids"]
            # The continuing run is the SAME stream: its checkpoints
            # keep the stream identity (incl. a healed stream's base).
            ckpt_metadata.update(
                data_seed=data_seed,
                exclude_ids=list(exclude_ids),
                stream_base_step=stream_plan["stream_base_step"],
            )

        loop_config = LoopConfig(
            total_steps=args.steps,
            log_every=args.log_every,
            checkpoint_every=(
                args.checkpoint_every if args.snapshot_path else 0
            ),
            eval_every=args.eval_every,
            checkpoint_dir=args.snapshot_path,
            resume=not args.no_resume,
            profile_dir=args.profile_dir,
            device_prefetch=args.device_prefetch,
            async_eval=args.async_eval,
            # Numerics flight recorder (obs/numerics.py): the in-step
            # summary gate; the provenance dump lands in the obs dir (or
            # --log-dir without one) on a tripped finite-check either way.
            numerics=getattr(args, "numerics", False),
            numerics_dump_dir=numerics_dump_dir,
            rng_seed=args.seed,
            ckpt_metadata=ckpt_metadata,
        )
        run_eval_fn = (
            eval_fn
            if (args.eval_every or args.dataset_type in ("coco", "pascal")
                or (args.dataset_type == "csv" and val_ds is not None))
            else None
        )

        # Self-healing numerics resume (--auto-resume): each attempt gets
        # a fresh pipeline (reseeded, poison ids excluded) and a fresh
        # state template; run_training's resume restores the last HEALTHY
        # checkpoint (the pre-save gate keeps poisoned states off disk).
        # data_seed/exclude_ids/skip_batches start from the elastic plan
        # above (a virgin run: args.seed, none, 0).
        attempt = 0
        injector_latch = {"done": False}  # one injection per PROCESS
        while True:
            train_batches = build_pipeline(
                train_ds,
                PipelineConfig(
                    batch_size=local_batch, shuffle=True,
                    transform=train_transform,
                    shard_index=shard_index, shard_count=shard_count,
                    skip_batches=skip_batches, exclude_ids=exclude_ids,
                    **{**pipe_common, "seed": data_seed},
                ),
                train=True,
            )
            batches = train_batches
            if getattr(args, "inject_nan_step", None):
                batches = _NanInjector(
                    train_batches, args.inject_nan_step, injector_latch
                )
            try:
                state = run_training(
                    model,
                    state,
                    batches,
                    num_classes,
                    loop_config,
                    mesh=mesh,
                    schedule=schedule,
                    anchor_config=anchor_config,
                    shard_weight_update=shard_update,
                    comm=comm_cfg,
                    topology=comm_topology,
                    allow_data_axis_divergence=args.allow_data_axis_divergence,
                    eval_fn=run_eval_fn,
                    logger=logger,
                )
                break
            except FloatingPointError as exc:
                attempt += 1
                plan = _auto_resume_plan(args, attempt, exc)
                if plan is None:
                    raise
                data_seed, exclude_ids = (
                    plan["data_seed"],
                    tuple(sorted(set(exclude_ids) | set(plan["exclude_ids"]))),
                )
                # A reseed is a NEW deterministic order starting at the
                # restore step: skip nothing, and record the effective
                # stream facts (seed, exclusions, base step) in every
                # subsequent checkpoint's manifest so a later
                # --resume-elastic re-derives THIS stream's position —
                # not the aborted original's (which would silently
                # replay/skip batches).
                skip_batches = 0
                ckpt_metadata.update(
                    data_seed=data_seed,
                    exclude_ids=list(exclude_ids),
                    stream_base_step=plan["restored_step"],
                )
                # ONE structured auto_resume event per resume — in the
                # JSONL next to the metrics it interrupts, and on stderr
                # for bare runs.
                payload = {**plan, "exclude_ids": list(exclude_ids)}
                logger.event("auto_resume", **payload)
                print(
                    json.dumps({"event": "auto_resume", **payload}),
                    file=sys.stderr, flush=True,
                )
                state = build_state()
            finally:
                # Deterministic pipeline teardown (previously left to the
                # GC finalizer): decode workers/threads are reaped HERE,
                # so shm workers export their trace files BEFORE main()'s
                # obs finalize merges — a GC-time close would orphan them
                # from trace.json.
                train_batches.close()
        return {"final_step": float(int(state.step))}
    finally:
        if slo_monitor is not None:
            slo_monitor.stop()
        if telem_server is not None:
            telem_server.close()


if __name__ == "__main__":
    main()
