#!/usr/bin/env python
"""Convert a training snapshot into serialized inference artifacts.

Parity with keras-retinanet's ``bin/convert_model.py`` (SURVEY.md M3): the
reference turned a training ``.h5`` into an inference model with anchors,
box decoding, clipping, and NMS appended.  Here the equivalent is exporting
the jitted detection program (forward → decode → clip → on-device batched
NMS, evaluate/detect.py) to self-contained StableHLO with the trained params
baked in — loadable with jax alone, no framework code (evaluate/export.py).

    python convert_model.py --snapshot-path ckpts --output exported \
        --num-classes 80 --backbone resnet50 --norm frozen_bn

One artifact is written per static shape bucket; ``--platforms cpu,tpu``
lowers each for several backends at once.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--snapshot-path", required=True,
                   help="orbax checkpoint directory (train.py --snapshot-path)")
    p.add_argument("--output", required=True, help="export directory")
    p.add_argument("--num-classes", type=int, required=True)
    from batchai_retinanet_horovod_coco_tpu.models.retinanet import BACKBONES

    p.add_argument("--backbone", default="resnet50", choices=BACKBONES)
    p.add_argument("--norm", default="gn", choices=["gn", "bn", "frozen_bn"])
    p.add_argument("--stem", default="space_to_depth",
                   choices=["conv", "space_to_depth", "space_to_depth4"],
                   help="stem formulation (param layout is identical; "
                        "either loads any snapshot)")
    p.add_argument("--f32", action="store_true",
                   help="compute in float32 (default bfloat16)")
    p.add_argument("--batch-size", type=int, default=None,
                   help="single exported batch size (shorthand for "
                        "--batch-sizes N; default 1)")
    p.add_argument("--batch-sizes", default=None, metavar="B1,B2",
                   help="comma-separated batch sizes: one artifact per "
                        "(bucket, batch) — the serve batcher pads a "
                        "partial batch to the smallest exported size "
                        "that fits it (serve/engine.py)")
    p.add_argument("--buckets", default=None, metavar="HxW,HxW",
                   help="explicit (H, W) shape buckets (e.g. "
                        "800x1344,1344x800); default: the pipeline's "
                        "default_buckets for the image sides, i.e. the "
                        "shapes an eval run actually emits")
    p.add_argument("--image-min-side", type=int, default=800)
    p.add_argument("--image-max-side", type=int, default=1333)
    p.add_argument("--score-threshold", type=float, default=0.05)
    p.add_argument("--nms-threshold", type=float, default=0.5)
    p.add_argument("--max-detections", type=int, default=300)
    from batchai_retinanet_horovod_coco_tpu.utils.cli import add_anchor_flags

    add_anchor_flags(p)
    p.add_argument("--export-version", default=None, metavar="VERSION",
                   help="rollout identity recorded in the manifest (the "
                        "serve fleet's router/canary gate attributes "
                        "per-replica health by it; default: the export "
                        "directory's basename at load time)")
    p.add_argument("--platforms", default=None,
                   help="comma-separated lowering targets, e.g. cpu,tpu "
                        "(default: the current backend only)")
    p.add_argument("--platform", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="backend to run the export trace on")
    return p


def parse_buckets(text: str) -> tuple[tuple[int, int], ...]:
    """'800x1344,1344x800' → ((800, 1344), (1344, 800))."""
    buckets = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            h, w = part.lower().split("x")
            buckets.append((int(h), int(w)))
        except ValueError:
            raise SystemExit(f"--buckets: not an HxW shape: {part!r}")
    if not buckets:
        raise SystemExit("--buckets: empty bucket list")
    return tuple(buckets)


def parse_batch_sizes(args) -> tuple[int, ...]:
    if args.batch_sizes is not None and args.batch_size is not None:
        raise SystemExit("pass --batch-size OR --batch-sizes, not both")
    if args.batch_sizes is not None:
        try:
            sizes = tuple(
                int(v) for v in args.batch_sizes.split(",") if v.strip()
            )
        except ValueError:
            raise SystemExit(
                f"--batch-sizes: not an int list: {args.batch_sizes!r}"
            )
        if not sizes or any(b < 1 for b in sizes):
            raise SystemExit(f"--batch-sizes: bad sizes {args.batch_sizes!r}")
        return tuple(sorted(set(sizes)))
    return (args.batch_size if args.batch_size is not None else 1,)


def main(argv: list[str] | None = None) -> str:
    args = build_parser().parse_args(argv)

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp
    import optax

    from batchai_retinanet_horovod_coco_tpu.data.pipeline import default_buckets
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import DetectConfig
    from batchai_retinanet_horovod_coco_tpu.evaluate.export import export_model
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        CheckpointManager,
        latest_step,
    )

    if latest_step(args.snapshot_path) is None:
        raise SystemExit(f"no checkpoint found under {args.snapshot_path}")

    from batchai_retinanet_horovod_coco_tpu.utils.cli import resolve_anchor_config

    # Flags + the anchor config train.py persisted beside the checkpoint
    # (conflicting flags abort; no flags = the saved config).
    anchor_config = resolve_anchor_config(args, args.snapshot_path)
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=args.num_classes,
            backbone=args.backbone,
            norm_kind=args.norm,
            stem=args.stem,
            anchor=anchor_config,
            dtype=jnp.float32 if args.f32 else jnp.bfloat16,
        )
    )
    buckets = (
        parse_buckets(args.buckets)
        if args.buckets
        else default_buckets(args.image_min_side, args.image_max_side)
    )
    batch_sizes = parse_batch_sizes(args)
    state = create_train_state(
        model, optax.sgd(0.01), (1, *buckets[0], 3), jax.random.key(0)
    )
    # Metadata-driven restore: only params/batch_stats/step are needed, so
    # the snapshot's optimizer never has to be reconstructed here.
    restored = CheckpointManager(args.snapshot_path).restore_arrays()
    state = state.replace(
        step=restored["step"],
        params=restored["params"],
        batch_stats=restored["batch_stats"],
    )
    print(f"restored step {int(state.step)} from {args.snapshot_path}")

    platforms = tuple(args.platforms.split(",")) if args.platforms else None
    manifest = export_model(
        state,
        model,
        args.output,
        buckets,
        batch_sizes,
        DetectConfig(
            score_threshold=args.score_threshold,
            iou_threshold=args.nms_threshold,
            max_detections=args.max_detections,
            anchor=anchor_config,
        ),
        platforms=platforms,
        image_min_side=args.image_min_side,
        image_max_side=args.image_max_side,
        version=args.export_version,
    )
    sizes = {
        e: os.path.getsize(os.path.join(args.output, e))
        for e in os.listdir(args.output)
    }
    for name, size in sorted(sizes.items()):
        print(f"  {name}: {size / 1e6:.1f} MB")
    print(f"wrote {manifest}")
    return manifest


if __name__ == "__main__":
    main()
