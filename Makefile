# Cluster lifecycle targets — the operator surface of the reference's W3/W4
# layer (SURVEY.md §2.1: Makefile + Batch AI cluster/job JSON), retargeted at
# Cloud TPU pod slices.  Every target delegates to launch/cluster.py, which
# is unit-tested and supports DRY=1 to print the gcloud command instead of
# running it.
#
#   make create NAME=ret-pod ACCEL=v5litepod-256
#   make submit NAME=ret-pod TRAIN_ARGS="--preset pod coco /mnt/coco"
#   make status NAME=ret-pod
#   make delete NAME=ret-pod
#   make test | make bench | make smoke

NAME ?= retinanet-pod
ZONE ?= us-east5-b
ACCEL ?= v5litepod-256
TRAIN_ARGS ?= --preset pod coco /mnt/coco
DRY ?=
DRYFLAG = $(if $(DRY),--dry-run,)
CLUSTER = python -m batchai_retinanet_horovod_coco_tpu.launch.cluster

.PHONY: create submit status delete test test-timings smoke bench \
	bench-check bench-pipeline pipebench pipebench-check evalbench \
	evalbench-check servebench servebench-check canaries \
	convergence-full lint lint-obs check-static tune-smoke tunebench \
	tunebench-check perf-report perf-report-check telemetry-smoke \
	numerics-smoke chaos chaos-smoke chaos-comm ckptbench \
	ckptbench-check fleet-smoke fleet-obs-smoke stream-smoke scale-smoke \
	commbench \
	commbench-check

create:
	$(CLUSTER) create --name $(NAME) --zone $(ZONE) --accelerator $(ACCEL) $(DRYFLAG)

submit:
	$(CLUSTER) submit --name $(NAME) --zone $(ZONE) $(DRYFLAG) -- $(TRAIN_ARGS)

status:
	$(CLUSTER) status --name $(NAME) --zone $(ZONE) $(DRYFLAG)

delete:
	$(CLUSTER) delete --name $(NAME) --zone $(ZONE) $(DRYFLAG)

test:
	python -m pytest tests/ -q

# Regenerate the committed per-test timing snapshot (budget mechanism,
# tests/conftest.py): run the fast tier, write TEST_TIMINGS.md.  Timings
# include each unique program's once-per-session compile (the cache is
# per-session; see conftest.py).
# bash + pipefail: a failing tier must NOT regenerate/bless the snapshot.
test-timings:
	bash -o pipefail -c 'python -m pytest tests/ -q -m "not slow" \
	  --durations=40 | tee /tmp/fast_tier_timings.log'
	python scripts/update_test_timings.py /tmp/fast_tier_timings.log

# End-to-end synthetic smoke on a virtual CPU mesh (no data, no TPU needed).
smoke:
	python train.py synthetic --platform cpu --backbone resnet_test --f32 \
	  --image-min-side 64 --image-max-side 64 --batch-size 8 --num-devices 8 \
	  --steps 20 --synthetic-size 64

bench:
	python bench.py

# Regression tripwire: flagship-bucket TRAIN bench vs the committed
# BUCKETBENCH.json number, THEN the eval/detect fast path vs the committed
# EVALBENCH.json number, THEN the serve closed loop vs the committed
# SERVEBENCH.json number — all with the 3% noise band (exit 1 on any
# regression).  Every mode probes the TPU first and classifies a tunnel
# outage as ONE structured JSON line + exit 75, never an rc-1 traceback.
bench-check:
	BENCH_SWEEP=0 BENCH_NUMERICS=0 BENCH_CHECK=1 python bench.py
	BENCH_SWEEP=0 EVALBENCH_E2E=0 BENCH_CHECK=1 python bench.py --mode eval
	BENCH_SWEEP=0 SERVEBENCH_OVERLOAD=0 SERVEBENCH_E2E=0 BENCH_CHECK=1 python bench.py --mode serve
	$(MAKE) commbench-check
	$(MAKE) perf-report-check
	$(MAKE) telemetry-smoke

# Eval/detect fast-path bench (ISSUE 2): per-bucket AOT detect + NMS-only
# ms/batch + sequential-vs-pipelined end-to-end comparison, one JSON line.
# evalbench-check is its regression tripwire (same policy as bench-check;
# a device-kind mismatch vs the committed artifact passes with a loud
# note to re-capture).
evalbench:
	python bench.py --mode eval

evalbench-check:
	BENCH_SWEEP=0 EVALBENCH_E2E=0 BENCH_CHECK=1 python bench.py --mode eval

# Dynamic-batching serve bench (ISSUE 4): per-bucket closed-loop server
# throughput vs the in-run detect ceiling (vs_ceiling ≥ 0.9 is the chip
# acceptance bar), request p50/p99, and an overload leg proving bounded
# queues SHED instead of queueing unboundedly.  servebench-check is the
# regression tripwire (same floor/device-class policy as bench-check).
# The continuous-vs-deadline leg (ISSUE 14) races the same seeded
# open-loop mixed-arrival schedule in both batching modes: the capture
# (servebench) runs it on the live flagship executable with the in-run
# bit-identity cross-check (SERVEBENCH_E2E=1 default); the check runs
# the device-independent stub fast path (SERVEBENCH_E2E=0) and enforces
# occupancy-strictly-above + the p99 no-worse band + the committed
# occupancy floor.
servebench:
	python bench.py --mode serve

servebench-check:
	BENCH_SWEEP=0 SERVEBENCH_OVERLOAD=0 SERVEBENCH_E2E=0 BENCH_CHECK=1 python bench.py --mode serve

# All four XLA-partitioner canaries in one shot (VERDICT r5 next-round #5):
# each asserts its bug's PRESENCE on the current jax/XLA (or skips when the
# installed version doesn't exhibit it) — a flip after a jax upgrade is the
# signal to re-measure the guards.  Filing-ready upstream text per repro:
# scripts/xla_repros/ISSUES.md.
canaries:
	python -m pytest tests/distributed/test_spatial_train.py -q -k canary

# Invariant lint engine (ISSUE 5): project-wide AST passes encoding the
# repo's concurrency/jit/clock/collective contracts — bounded-queues,
# thread-error-contract, jit-purity, monotonic-clock, collective-safety,
# watchdog-coverage — against the committed baseline
# (batchai_retinanet_horovod_coco_tpu/analysis/baseline.json; new findings
# fail, fixed grandfathered ones must be removed via --update-baseline, so
# the baseline only shrinks).  `make lint` = engine + both legacy audits
# (the watchdog shim, and the HLO collective audit at reduced width on a
# tiny virtual mesh — the slow leg, ~1 min of XLA compile).  Suppression
# grammar: '# lint: <rule>: <why>' with a REQUIRED rationale.  Also runs
# in tier-1 (tests/unit/test_lint.py::TestLiveTree).
# --jobs 8: the per-file phase fans out over a thread pool (ISSUE 20);
# the report is byte-identical to the serial run.
lint:
	python -m batchai_retinanet_horovod_coco_tpu.analysis --jobs 8
	python scripts/audit_threads.py
	python scripts/audit_collectives.py --reduced --devices 2

# Live telemetry smoke (ISSUE 9): CPU serve smoke over a stub engine →
# scrape + schema-check GET /metrics (request-latency summary, shed
# counters, queue-depth gauges, Prometheus text format) and GET /healthz
# (200 live → 503 naming the stalled component under an injected
# watchdog stall → recovery), plus the registry-vs-snapshot consistency
# check.  No chip, no dataset — CI-safe; also aggregated into
# check-static and bench-check.
telemetry-smoke:
	JAX_PLATFORMS=cpu python scripts/telemetry_smoke.py

# Numerics flight recorder smoke (ISSUE 10): CPU train smoke with an
# injected mid-run NaN → asserts, without any rerun, that ONE
# NUMERICS_DUMP.json lands naming the first non-finite layer, the
# built-in nonfinite SLO rule fires EXACTLY ONCE (metrics.jsonl + trace
# timeline), the auto-emitted PERF_REPORT ranks the numerics:divergence
# verdict #1, and the numerics-off step leaks no summary keys.  No chip,
# no dataset — CI-safe; aggregated into check-static.
numerics-smoke:
	JAX_PLATFORMS=cpu python scripts/numerics_smoke.py

# Fault-injection harness (ISSUE 11, scripts/chaos.py): SIGKILL a real
# CPU training subprocess at every phase of the checkpoint write protocol
# (snapshot, tmp-write, manifest-commit, rename, finalize — >= 20
# scheduled kills) plus mid-step external kills, manufactured torn
# checkpoint dirs, and an injected-NaN --auto-resume leg; asserts a
# restorable checkpoint survives EVERY kill and the resumed run's losses
# are bit-identical to an uninterrupted baseline (--resume-elastic
# re-derives the stream position).  chaos-smoke is the bounded CI leg
# (one mid-save kill + the NaN leg, ~4 subprocess runs).
chaos:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/chaos.py

chaos-smoke:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/chaos.py --smoke

# COMMBENCH (ISSUE 13, bench.py --mode comm + scripts/commbench_sweep.py):
# the gradient-compression subsystem's committed evidence — bytes-on-wire
# vs exact (the <= 0.65x claim), step-time delta, and parity drift after
# N identical steps, per variant (int8 / int8+overlap / bf16 / 1MB
# buckets), on a forced 8-device virtual CPU mesh (bytes + parity are
# device-independent; timing is indicative).  commbench-check is the
# tripwire: int8-only re-measure vs the committed COMMBENCH.json (bytes
# ratio hard <= 0.65 AND <= committed + 0.02, drift band, device-class
# guard) with the exit-75 outage contract from bench.py's shared probe.
commbench:
	JAX_PLATFORMS=cpu python scripts/commbench_sweep.py

commbench-check:
	JAX_PLATFORMS=cpu BENCH_SWEEP=0 BENCH_CHECK=1 python bench.py --mode comm

# Comm chaos leg alone (ISSUE 13, scripts/chaos.py --comm): SIGKILL a
# compressed+EF training run mid-save, assert the resume restores the EF
# residual state from the checkpoint (or cleanly zeros it with ONE
# structured ef_reset event) and the losses rejoin the uninterrupted
# baseline envelope.  Also part of the full `make chaos` schedule.
chaos-comm:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/chaos.py --comm

# Serve-fleet chaos (ISSUE 12, scripts/chaos.py --serve): the REAL fleet
# CLI over 2 stub-engine replica subprocesses — SIGKILL one mid-load and
# assert every request completes or sheds WITH A REASON (zero hung
# clients, zero silent drops), the router's /healthz stays 200 and its
# /metrics scrape carries the fleet families throughout, and the circuit
# breaker readmits the replica after the supervisor respawns it; then a
# deliberately slow stub canary behind the SLO gate must produce EXACTLY
# ONE canary_rollback event with the fleet back at baseline weights.
# CPU-only, no dataset — wired into check-static.
fleet-smoke:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/chaos.py --serve

# Fleet observability smoke (ISSUE 15, scripts/fleet_obs_smoke.py): the
# real fleet CLI + 2 stub replicas with --obs-trace on — SIGKILL one
# replica (exactly ONE fleet-availability slo_violation, breaker readmits
# the respawn), force a shed-driven re-dispatch with both replicas alive
# (one trace id, serve_request spans on BOTH replica tracks of the merged
# trace.json), check federated fleet /metrics equals each replica's own
# exposition after quiescing, and run `obs.analyze --fleet` over the
# artifacts — the verdict must NAME the killed replica.  CPU-only, no
# dataset — wired into check-static.
fleet-obs-smoke:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/fleet_obs_smoke.py

# Streaming detection smoke (ISSUE 18, scripts/stream_smoke.py): the real
# fleet CLI + 2 stub-video replicas — 3 seeded drift streams race
# single-image traffic over HTTP /stream/*, the frame-delta cache must
# hit on the drift plateaus, track ids must hold stable between scene
# cuts, and a mid-stream SIGKILL of a pinned replica must re-pin each of
# its streams with exactly one stream_repinned event and ZERO dropped
# frames.  CPU-only, no dataset — wired into check-static.
stream-smoke:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/stream_smoke.py

# Autoscaling smoke (ISSUE 19, scripts/chaos.py --autoscale): the seeded
# diurnal/spike day against a real 1..3 autoscaling stub fleet — the
# spike must scale 1→N (a mid-spike SIGKILL is repaired through the
# respawn budget), the quiet tail must scale back to 1, and every
# request resolves (zero hangs, zero silent drops); then the cold tier:
# an idle min_replicas=0 fleet reaches ZERO replicas and the first
# request's shed (demand_scale_from_zero) respawns capacity so the
# client's retry lands.  CPU-only, no dataset — wired into check-static.
scale-smoke:
	JAX_PLATFORMS=cpu RETINANET_LOCK_DEBUG=1 python scripts/chaos.py --autoscale

# CKPTBENCH (ISSUE 11): the two durability numbers — async-save overhead
# (wall of N checkpointed steps vs the same N without) and resume
# time-to-first-step — committed as CKPTBENCH.json.  ckptbench-check
# re-measures with bench-check's device-class guard (cross-class
# comparisons pass with a loud re-capture note) and the exit-75 outage
# contract when CKPTBENCH_PLATFORM targets a real accelerator; the band
# is wide (CKPTBENCH_BAND, default 75%) because subprocess wall times on
# small shared boxes are noise-dominated.
ckptbench:
	JAX_PLATFORMS=cpu python scripts/chaos.py --bench

ckptbench-check:
	JAX_PLATFORMS=cpu python scripts/chaos.py --bench --check

# bench-check-style aggregate for everything chip-free: one target CI can
# run without touching an accelerator (chaos-smoke DOES run a few real
# CPU training subprocesses over generated synthetic data — budget the
# job for minutes, not seconds).
check-static: lint telemetry-smoke numerics-smoke chaos-smoke fleet-smoke fleet-obs-smoke stream-smoke scale-smoke
	@echo "check-static: lint engine + watchdog audit + HLO collective audit + telemetry smoke + numerics smoke + chaos smoke + fleet smoke + fleet obs smoke + stream smoke + scale smoke all green"

# Static watchdog-coverage audit alone (ISSUE 3; now a shim over the lint
# engine's watchdog-coverage rule — same CLI, same exit codes).  Also runs
# in tier-1 (tests/unit/test_obs.py::test_audit_threads_clean).
lint-obs:
	python scripts/audit_threads.py

# Schedule autotuner (ISSUE 6, tune/): measured search over the tunable
# hot-path parameters — Pallas tile/block shapes (focal, matching, NMS),
# pre_nms_size, per-bucket batch sizes — per device_kind; winners land in
# artifacts/schedules/<device_kind>.json, which train/eval/serve/export
# resolve at compile time (RUNBOOK "Autotuning schedules").
#
# tune-smoke: CPU-sized end-to-end proof (tiny bucket, xla winners,
# pallas candidates recorded as skipped) into a throwaway registry dir —
# CI-safe, never mutates the committed registry.
tune-smoke:
	python -m batchai_retinanet_horovod_coco_tpu.tune --smoke \
	  --ops nms,focal,matching --batch-axis \
	  --out-root /tmp/tune_smoke_schedules

# tunebench: the real search on THIS device (probe + exit-75 outage
# contract) — writes the device's registry artifact AND the committed
# TUNEBENCH.json tripwire record (the NMS winner's measured ms/batch).
tunebench:
	python -m batchai_retinanet_horovod_coco_tpu.tune --batch-axis \
	  --bench-out TUNEBENCH.json

# tunebench-check: re-measure the committed TUNEBENCH winner and enforce
# the +3% ms ceiling — same device-class guard as bench-check (a record
# captured on another device class passes with a loud re-capture note).
tunebench-check:
	python -m batchai_retinanet_horovod_coco_tpu.tune --check

# Perf doctor (ISSUE 8, obs/analyze): turn an obs dir's own artifacts
# (merged trace.json + metrics.jsonl) into one machine-readable
# PERF_REPORT.json — step-time decomposition, pipeline overlap
# efficiency, queue/stall correlation, MFU estimate, ranked top-3
# bottleneck verdict (RUNBOOK "Perf doctor").  perf-report analyzes an
# existing obs dir (OBS_DIR, default artifacts/obs — any --obs-trace run
# auto-emits the same report at exit; this target is the post-hoc path).
OBS_DIR ?= artifacts/obs
perf-report:
	python -m batchai_retinanet_horovod_coco_tpu.obs.analyze $(OBS_DIR)

# perf-report-check: regression tripwire — run the standard traced CPU
# smoke (train+eval, ~2 min; --platform cpu so the attribution baseline
# is device-stable), analyze it, schema-validate the report, and enforce
# the attribution-fraction band (PERF_BAND_ABS, default ±0.20 absolute)
# against the committed repo-root PERF_REPORT.json — same device-class
# guard as bench-check (a baseline captured on another device class
# passes with a loud re-capture note).
PERF_OBS_DIR ?= /tmp/perf_report_check_obs
perf-report-check:
	rm -rf $(PERF_OBS_DIR)
	python train.py synthetic --platform cpu --backbone resnet_test --f32 \
	  --image-min-side 64 --image-max-side 64 --batch-size 4 \
	  --num-devices 1 --steps 20 --eval-every 10 --synthetic-size 64 \
	  --synthetic-root /tmp/perf_report_check_data \
	  --obs-trace --obs-dir $(PERF_OBS_DIR)
	python -m batchai_retinanet_horovod_coco_tpu.obs.analyze \
	  $(PERF_OBS_DIR) --check

# Host input-pipeline bench: threads-vs-procs sweep (bench_pipeline.py).
# pipebench-check is the regression tripwire twin of bench-check: measured
# best vs the committed PIPEBENCH.json value minus the noise band (exit 1).
bench-pipeline: pipebench
pipebench:
	python bench_pipeline.py

pipebench-check:
	python bench_pipeline.py --check

# Flagship-resolution convergence artifact (VERDICT r2 #2): the REAL recipe
# — resnet50 frozen_bn, multistep decays at 2/3 and 8/9 of --steps, warmup,
# weight decay — at the 800x1344 bucket, on synthetic data generated at
# exactly that shape, on the real chip, through the CLI.  Writes
# artifacts/convergence_full/metrics.jsonl (train curve + eval mAP at each
# --eval-every); the committed copy is the evidence, rerunnable with this
# one command (~45 min on v5e-1; host-pipeline-bound on few-core boxes).
# --lr 0.16 at global batch 8 = effective peak 5e-3 under the linear-scaling
# rule (train/optim.py: lr * global_batch / 256 — the reference's hvd.size()
# scaling, which a single-chip run must compensate for).
convergence-full:
	python train.py synthetic --synthetic-size 800x1344 --synthetic-images 64 \
	  --synthetic-classes 3 --synthetic-root /tmp/synthetic_coco_full \
	  --backbone resnet50 --norm frozen_bn --batch-size 8 --lr 0.16 \
	  --steps 2500 --warmup-steps 250 --schedule multistep \
	  --image-min-side 800 --image-max-side 1344 \
	  --eval-every 500 --log-every 50 --workers 8 \
	  --snapshot-path /tmp/convergence_full_ckpt --checkpoint-every 500 \
	  --log-dir artifacts/convergence_full
