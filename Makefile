# Cluster lifecycle targets — the operator surface of the reference's W3/W4
# layer (SURVEY.md §2.1: Makefile + Batch AI cluster/job JSON), retargeted at
# Cloud TPU pod slices.  Every target delegates to launch/cluster.py, which
# is unit-tested and supports DRY=1 to print the gcloud command instead of
# running it.
#
#   make create NAME=ret-pod ACCEL=v5litepod-256
#   make submit NAME=ret-pod TRAIN_ARGS="--preset pod coco /mnt/coco"
#   make status NAME=ret-pod
#   make delete NAME=ret-pod
#   make test | make bench | make smoke

NAME ?= retinanet-pod
ZONE ?= us-east5-b
ACCEL ?= v5litepod-256
TRAIN_ARGS ?= --preset pod coco /mnt/coco
DRY ?=
DRYFLAG = $(if $(DRY),--dry-run,)
CLUSTER = python -m batchai_retinanet_horovod_coco_tpu.launch.cluster

.PHONY: create submit status delete test smoke bench

create:
	$(CLUSTER) create --name $(NAME) --zone $(ZONE) --accelerator $(ACCEL) $(DRYFLAG)

submit:
	$(CLUSTER) submit --name $(NAME) --zone $(ZONE) $(DRYFLAG) -- $(TRAIN_ARGS)

status:
	$(CLUSTER) status --name $(NAME) --zone $(ZONE) $(DRYFLAG)

delete:
	$(CLUSTER) delete --name $(NAME) --zone $(ZONE) $(DRYFLAG)

test:
	python -m pytest tests/ -q

# End-to-end synthetic smoke on a virtual CPU mesh (no data, no TPU needed).
smoke:
	python train.py synthetic --platform cpu --backbone resnet_test --f32 \
	  --image-min-side 64 --image-max-side 64 --batch-size 8 --num-devices 8 \
	  --steps 20 --synthetic-size 64

bench:
	python bench.py
