#!/usr/bin/env python
"""Dataset/anchor debugging CLI.

Parity with keras-retinanet's ``bin/debug.py`` (SURVEY.md M12), rethought for
a headless TPU VM: instead of an interactive cv2 window it (a) prints
per-image anchor-assignment statistics (positives / negatives / ignored, by
the same on-device matching the train step uses), and (b) optionally writes
annotated JPEGs (gt boxes green, positive anchors blue) to ``--output-dir``.

Usage:
  python debug.py coco /data/coco [--limit 8] [--output-dir /tmp/vis]
  python debug.py synthetic [--limit 8]
  python debug.py buckets /data/coco/annotations/instances_train2017.json
  python debug.py nans NUMERICS_DUMP.json

``nans`` is the numerics-triage driver (ISSUE 10): pretty-print the
NUMERICS_DUMP.json the train loop's abort path landed (obs/numerics.py
``provenance`` — first non-finite layer/loss term, batch source ids,
per-layer stats; no ``--debug-nans`` rerun was needed to produce it).
The localization logic lives ENTIRELY in obs/numerics.py — this
subcommand is a thin formatter over ``load_dump``/``format_dump``.

``buckets`` derives the EXACT static-bucket shares for a dataset from the
annotation file alone (COCO records carry width/height; nothing is
decoded): for every image it applies the reference resize rule + bucket
pick the pipeline uses (data/pipeline.resize_scale/pick_bucket) and prints
per-bucket image counts/shares — the measured replacement for the
estimated COCO aspect shares baked into bench.py's weighted mix
(BUCKETBENCH.json).  With --bucketbench it also recomputes the
mix-weighted imgs/s/chip from the recorded per-bucket rates.
"""

from __future__ import annotations

import argparse
import os

import jax
import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="dataset_type", required=True)
    coco = sub.add_parser("coco")
    coco.add_argument("coco_path")
    coco.add_argument("--annotations", default="annotations/instances_train2017.json")
    coco.add_argument("--images", default="train2017")
    synth = sub.add_parser("synthetic")
    synth.add_argument("--synthetic-root", default="/tmp/synthetic_coco_debug")
    synth.add_argument("--synthetic-images", type=int, default=8)
    synth.add_argument("--synthetic-size", type=int, default=256)
    nans = sub.add_parser(
        "nans", help="triage a NUMERICS_DUMP.json (obs/numerics.py)"
    )
    nans.add_argument("dump_file", help="path to a NUMERICS_DUMP.json "
                      "written by the train loop's non-finite abort")
    nans.add_argument("--json", action="store_true", dest="as_json",
                      help="re-emit the dump as one JSON line (machine "
                           "consumers) instead of the human triage view")
    bk = sub.add_parser("buckets")
    bk.add_argument("annotation_file")
    bk.add_argument("--image-min-side", type=int, default=800)
    bk.add_argument("--image-max-side", type=int, default=1333)
    bk.add_argument(
        "--bucketbench", default=None,
        help="path to a BUCKETBENCH.json; recompute its weighted_mix "
        "with the measured shares",
    )
    for sp in (coco, synth):
        sp.add_argument("--limit", type=int, default=8)
        sp.add_argument("--image-min-side", type=int, default=800)
        sp.add_argument("--image-max-side", type=int, default=1333)
        sp.add_argument("--max-gt", type=int, default=None,
                        help="gt padding; default auto-sizes to the dataset")
        sp.add_argument("--output-dir", default=None)
        # Same anchor surface as train.py (utils/cli.py), so assignment
        # statistics reflect the anchors a run would actually train with.
        from batchai_retinanet_horovod_coco_tpu.utils.cli import add_anchor_flags

        add_anchor_flags(sp)
    return p


def bucket_shares(
    annotation_file: str, min_side: int, max_side: int
) -> dict[str, dict]:
    """Per-bucket image counts/shares for a COCO-format annotation file.

    Pure metadata pass (width/height from the records; no image decode):
    for each image, apply the pipeline's own resize rule and bucket pick
    (data/pipeline.resize_scale/pick_bucket over
    default_buckets(min_side, max_side)) and tally.
    """
    from batchai_retinanet_horovod_coco_tpu.data import CocoDataset
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        bucket_for_source,
        default_buckets,
    )

    dataset = CocoDataset(annotation_file, image_dir=".")
    buckets = default_buckets(min_side, max_side)
    counts: dict[tuple[int, int], int] = {b: 0 for b in buckets}
    for rec in dataset.records:
        counts[
            bucket_for_source(
                rec.height, rec.width, min_side, max_side, buckets
            )
        ] += 1
    total = max(sum(counts.values()), 1)
    return {
        f"{b[0]}x{b[1]}": {"count": n, "share": n / total}
        for b, n in counts.items()
    }


def _run_buckets(args) -> dict:
    import json

    shares = bucket_shares(
        args.annotation_file, args.image_min_side, args.image_max_side
    )
    for name, row in shares.items():
        print(f"{name}: {row['count']} images ({row['share']:.1%})")
    out = {"shares": shares}
    if args.bucketbench:
        with open(args.bucketbench) as f:
            bench = json.load(f)
        # Accept both schemas: the committed BUCKETBENCH.json (long keys)
        # and a saved `python bench.py` JSON line (short keys).
        rates = bench.get("per_bucket_imgs_per_sec_per_chip") or bench.get(
            "per_bucket"
        )
        if rates is None:
            raise SystemExit(
                f"{args.bucketbench}: no per-bucket rates found (expected "
                "'per_bucket_imgs_per_sec_per_chip' or bench.py's "
                "'per_bucket')"
            )
        recorded = bench.get(
            "weighted_mix_imgs_per_sec_per_chip", bench.get("weighted_mix")
        )
        missing = [
            name
            for name, row in shares.items()
            if row["share"] > 0 and name not in rates
        ]
        if missing:
            raise SystemExit(
                f"{args.bucketbench} has no rate for bucket(s) {missing} "
                f"(it records {sorted(rates)}): the bench was recorded at "
                "a different --image-min-side/--image-max-side bucket "
                "config — re-run bench.py at this config first"
            )
        # Harmonic mix: average seconds/image under the measured shares.
        cost = sum(
            row["share"] / rates[name]
            for name, row in shares.items()
            if row["share"] > 0
        )
        mix = 1.0 / cost if cost else None
        out["weighted_mix_imgs_per_sec_per_chip"] = mix
        if mix is None:
            print("no images landed in any bucket; weighted mix undefined")
        else:
            note = (
                f" (recorded estimate: {recorded})"
                if recorded is not None
                else ""
            )
            print(
                f"mix-weighted rate at these shares: {mix:.2f} "
                f"imgs/s/chip{note}"
            )
    return out


def _run_nans(args) -> dict:
    """Thin driver over obs/numerics.py — no tree-walk lives here."""
    import json

    from batchai_retinanet_horovod_coco_tpu.obs import numerics

    dump = numerics.load_dump(args.dump_file)
    if args.as_json:
        print(json.dumps(dump, sort_keys=True))
    else:
        print(numerics.format_dump(dump))
    return dump


def main(argv=None) -> list[dict]:
    args = build_parser().parse_args(argv)
    # Host debugging tool: tiny per-image ops, not worth a TPU round trip.
    jax.config.update("jax_platforms", "cpu")

    if args.dataset_type == "nans":
        return [_run_nans(args)]
    if args.dataset_type == "buckets":
        return [_run_buckets(args)]

    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
        make_synthetic_coco,
    )
    from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
    from batchai_retinanet_horovod_coco_tpu.ops import matching as matching_lib

    if args.dataset_type == "synthetic":
        size = (args.synthetic_size, args.synthetic_size)
        ann = make_synthetic_coco(
            args.synthetic_root, num_images=args.synthetic_images,
            image_size=size, split="train",
        )
        dataset = CocoDataset(ann, os.path.join(args.synthetic_root, "train"))
        args.image_min_side = min(args.image_min_side, size[0])
        args.image_max_side = min(args.image_max_side, size[1])
    else:
        dataset = CocoDataset(
            os.path.join(args.coco_path, args.annotations),
            os.path.join(args.coco_path, args.images),
        )

    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        default_buckets,
        resolve_max_gt,
    )
    from batchai_retinanet_horovod_coco_tpu.utils.cli import make_anchor_config

    anchor_config = make_anchor_config(args)
    buckets = default_buckets(args.image_min_side, args.image_max_side)
    pipe = build_pipeline(
        dataset,
        PipelineConfig(
            batch_size=1, buckets=buckets, min_side=args.image_min_side,
            max_side=args.image_max_side,
            max_gt=resolve_max_gt(args.max_gt, dataset),
            shuffle=False, hflip_prob=0.0, num_workers=2,
        ),
        train=False,
    )

    assign = jax.jit(
        lambda anchors, b, l, m: matching_lib.anchor_targets(
            anchors, b, l, m, dataset.num_classes, matching_lib.MatchingConfig()
        ),
        static_argnums=(),
    )
    anchor_cache: dict[tuple[int, int], np.ndarray] = {}
    report: list[dict] = []
    for batch in pipe:
        if len(report) >= args.limit:
            break
        hw = batch.images.shape[1:3]
        if hw not in anchor_cache:
            anchor_cache[hw] = anchors_lib.anchors_for_image_shape(
                hw, anchor_config
            )
        anchors = anchor_cache[hw]
        targets = assign(
            anchors, batch.gt_boxes[0], batch.gt_labels[0], batch.gt_mask[0]
        )
        state = np.asarray(targets.state)
        rec = {
            "image_id": int(batch.image_ids[0]),
            "gt": int(batch.gt_mask[0].sum()),
            "anchors": int(state.size),
            "positive": int((state == matching_lib.POSITIVE).sum()),
            "ignored": int((state == matching_lib.IGNORE).sum()),
        }
        rec["negative"] = rec["anchors"] - rec["positive"] - rec["ignored"]
        report.append(rec)
        print(
            f"image {rec['image_id']}: {rec['gt']} gt, {rec['anchors']} anchors "
            f"→ {rec['positive']} pos / {rec['ignored']} ignore / {rec['negative']} neg",
            flush=True,
        )
        if args.output_dir:
            _write_vis(args.output_dir, batch, anchors, state)

    unmatched = [r for r in report if r["gt"] > 0 and r["positive"] == 0]
    if unmatched:
        print(f"WARNING: {len(unmatched)} image(s) with gt but NO positive anchors")
    return report


def _write_vis(out_dir: str, batch, anchors: np.ndarray, state: np.ndarray) -> None:
    from PIL import Image, ImageDraw

    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        IMAGENET_MEAN,
        IMAGENET_STD,
    )

    os.makedirs(out_dir, exist_ok=True)
    if batch.images.dtype == np.uint8:  # pipeline default: raw uint8
        im = Image.fromarray(batch.images[0])
    else:  # host_normalize=True: invert the ImageNet normalization
        img = (batch.images[0] * IMAGENET_STD + IMAGENET_MEAN) * 255.0
        im = Image.fromarray(np.clip(img, 0, 255).astype(np.uint8))
    draw = ImageDraw.Draw(im)
    from batchai_retinanet_horovod_coco_tpu.ops.matching import POSITIVE

    for a in anchors[state == POSITIVE]:
        draw.rectangle([float(v) for v in a], outline=(60, 120, 255))
    for box, valid in zip(batch.gt_boxes[0], batch.gt_mask[0]):
        if valid:
            draw.rectangle([float(v) for v in box], outline=(40, 220, 40), width=2)
    im.save(os.path.join(out_dir, f"{int(batch.image_ids[0]):012d}.jpg"))


if __name__ == "__main__":
    main()
