#!/usr/bin/env python
"""Standalone COCO mAP evaluation CLI.

Parity with keras-retinanet's ``bin/evaluate.py`` (SURVEY.md M12): load a
snapshot, run the inference path (forward → decode → on-device batched NMS),
and print COCO mAP@[.5:.95] stats.  Thin shim over ``train.py --eval-only``
so the two surfaces can never drift.
"""

from __future__ import annotations

import sys


def main(argv: list[str] | None = None):
    import train

    argv = list(sys.argv[1:] if argv is None else argv)
    metrics = train.main(argv + ["--eval-only"])
    names = ("AP", "AP50", "AP75", "APsmall", "APmedium", "APlarge")
    for k in names:
        if k in metrics:
            print(f"{k}: {metrics[k]:.4f}")
    # VOC metrics (CSV datasets): voc_mAP first, then per-class APs in
    # numeric class-id order (string sort would put voc_AP_10 before voc_AP_2).
    # Only voc_* keys enter the sort: COCO keys like 'AP' have no '_' tail.
    def voc_order(k: str):
        tail = k.rsplit("_", 1)[-1]
        return (k != "voc_mAP", int(tail) if tail.isdigit() else 0, k)

    for k in sorted((k for k in metrics if k.startswith("voc_")), key=voc_order):
        print(f"{k}: {metrics[k]:.4f}")
    return metrics


if __name__ == "__main__":
    main()
