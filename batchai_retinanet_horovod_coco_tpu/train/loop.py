"""The training loop: the reference's ``model.fit_generator`` equivalent.

SURVEY.md call stack 3.2: Keras ``fit_generator`` + callback list
(BroadcastGlobalVariables, ModelCheckpoint, CocoEval, TensorBoard) becomes an
explicit step loop: pull a host batch, dispatch the jitted SPMD step for that
batch's shape bucket (one compiled program per bucket, cached here), log
device-averaged metrics, checkpoint/eval on schedule.  There is no broadcast
callback — initial weights are identical on every process by PRNG
construction (train/state.py) — and no RedirectModel/convert step.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import threading
import warnings
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh

from batchai_retinanet_horovod_coco_tpu import losses as losses_lib
from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.data.prefetch import prefetch_map
from batchai_retinanet_horovod_coco_tpu.ops import matching as matching_lib
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
    SPACE_AXIS,
    batch_sharding,
    replicated_sharding,
    spatial_batch_shardings,
)
from batchai_retinanet_horovod_coco_tpu.train import optim
from batchai_retinanet_horovod_coco_tpu.train.state import TrainState
from batchai_retinanet_horovod_coco_tpu.train.step import (
    make_train_step,
    make_train_step_spatial,
)
from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs import numerics as numerics_lib
from batchai_retinanet_horovod_coco_tpu.obs.events import device_memory_stats
from batchai_retinanet_horovod_coco_tpu.obs.numerics import NumericsConfig
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.train.state import model_variables
from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import CheckpointManager
from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger


# With --log-every 0 the loop still pulls the loss scalar at this cadence so
# a NaN cannot train garbage for the rest of a long run before aborting
# (SURVEY.md §5.2; the log-boundary-only check was a real hole at
# log_every=0).  One scalar fetch per window is noise next to step time.
_FINITE_CHECK_EVERY = 100


# The metrics whose finiteness gates checkpointing: ``loss`` witnesses the
# pre-update params, ``param_norm`` the post-update ones (a save at the very
# step whose update introduced the poison is only caught by the latter —
# the step-N loss is computed before the step-N update, train/step.py).
_SENTINEL_METRICS = ("loss", "param_norm")


def _abort_nonfinite(
    name: str,
    value: float,
    step: int,
    cadence: str,
    *,
    model=None,
    state=None,
    device_arrays: dict[str, Any] | None = None,
    image_ids=None,
    metrics=None,
    rng_seed: int | None = None,
    dump_dir: str | None = None,
    logger=None,
) -> None:
    """Numerical sanitizer abort (SURVEY.md §5.2), ISSUE-10 edition: run
    the provenance pass IN-PLACE on the already-poisoned state/batch and
    land ONE NUMERICS_DUMP.json before raising — no ``--debug-nans``
    rerun needed.  The dump can never mask the abort: a failing
    provenance pass degrades to one structured ``numerics_dump_error``
    stderr line and the original FloatingPointError still raises."""
    dump_path = None
    first = None
    try:
        dump = numerics_lib.provenance(
            step=step,
            metrics=metrics,
            params=state.params if state is not None else None,
            model=model,
            variables=(
                model_variables(state)
                if model is not None and state is not None
                else None
            ),
            images=(device_arrays or {}).get("images"),
            image_ids=image_ids,
            rng_seed=rng_seed,
            tripped={"metric": name, "value": float(value)},
            cadence=cadence,
        )
        first = dump.get("first_nonfinite")
        # The file needs a configured home (--obs-dir / --log-dir / the
        # LoopConfig field) — a bare run still gets the localization in
        # the exception message, but never litters the cwd.
        target_dir = dump_dir or trace.trace_dir()
        if target_dir:
            dump_path = numerics_lib.write_dump(dump, target_dir)
    except Exception as e:  # the abort must land with or without a dump
        print(
            json.dumps(
                {"event": "numerics_dump_error", "error": repr(e)[:500]}
            ),
            file=sys.stderr,
            flush=True,
        )
    # The trip lands on every read surface: trace timeline instant,
    # telemetry counter (the nonfinite SLO rule fires on it at the
    # monitor's drain poll), structured JSONL event.
    trace.instant(
        "numerics_trip", metric=name, step=step, value=float(value)
    )
    telemetry.record_nonfinite_trip(name)
    log_event = getattr(logger, "event", None)
    if log_event is not None:
        try:
            log_event(
                "numerics_trip",
                metric=name,
                step=step,
                value=float(value),
                dump=dump_path,
                first_nonfinite=first,
            )
        except Exception:
            pass  # a broken sink must not mask the abort
    located = f" (first non-finite: {first})" if first else ""
    if dump_path:
        where = f"provenance dump at {dump_path}{located}"
    elif first:
        where = (
            f"first non-finite: {first} (pass --obs-dir or --log-dir to "
            "keep the full NUMERICS_DUMP.json)"
        )
    else:
        where = "provenance dump failed — see numerics_dump_error on stderr"
    raise FloatingPointError(
        f"non-finite {name} ({float(value)}) at or before step {step} "
        f"(checked {cadence}); {where}"
    )


@dataclasses.dataclass(frozen=True)
class LoopConfig:
    total_steps: int = 1000
    log_every: int = 20
    checkpoint_every: int = 0  # 0 = no checkpointing
    eval_every: int = 0  # 0 = eval only at the end (if eval_fn given)
    checkpoint_dir: str | None = None
    resume: bool = True  # resume from latest checkpoint if present
    max_to_keep: int = 3
    # Profiling (SURVEY.md §5.1: jax.profiler replaces the reference's
    # nothing-beyond-TensorBoard): trace steps [profile_start_step,
    # profile_start_step + profile_steps) into profile_dir (per host).
    profile_dir: str | None = None
    profile_start_step: int = 10
    profile_steps: int = 5
    # Device-prefetch depth: a background thread pulls host batches and
    # enqueues their host→device transfers this many steps ahead, so step k
    # overlaps both batch k+1's DMA AND the host-side pipeline pull
    # (assembly, queue handoff).  2 = classic double buffering.  0 disables
    # the thread (transfer happens synchronously at each step — debugging).
    device_prefetch: int = 2
    # Run the mid-training eval hook in a background thread on a snapshotted
    # param copy instead of blocking the step stream (see _AsyncEvalRunner
    # for the safety contract; multi-process falls back to synchronous).
    # The FINAL eval stays synchronous either way.
    async_eval: bool = False
    # Numerics flight recorder (ISSUE 10, obs/numerics.py): fuse the
    # in-step grad/update health summary (global + per-group grad norms,
    # update/param ratio, non-finite count, cross-replica agreement) into
    # the compiled step.  Off (default) the compiled program and the
    # loop's record sites are unchanged (one bool check each).  The
    # NaN-provenance dump on a tripped finite-check is ALWAYS armed —
    # it only ever runs on the failure path.
    numerics: bool = False
    # Where NUMERICS_DUMP.json lands on a tripped finite-check; default =
    # the obs trace dir when tracing is on, else no file is written (the
    # abort message still carries the first-non-finite localization).
    numerics_dump_dir: str | None = None
    # Recorded in the provenance dump (reproduction context); train.py
    # passes --seed through.
    rng_seed: int | None = None
    # Recorded verbatim in every checkpoint manifest (utils/checkpoint.py).
    # train.py stores the data-order facts --resume-elastic re-derives the
    # stream position from (global batch size, data seed); anything a
    # future resume needs to validate against belongs here.
    ckpt_metadata: dict | None = None


def _device_batch(batch: Batch, mesh: Mesh | None) -> dict[str, Any]:
    """Host Batch → the device-resident dict the train step consumes.

    Multi-host: each process holds its LOCAL shard of the global batch; the
    global jax.Array is assembled per process via
    ``make_array_from_process_local_data`` (the grain idiom).  Single-host:
    explicit ``device_put`` (sharded over the mesh when present) so the
    host→device DMA is enqueued HERE — which lets ``_prefetch_to_device``
    overlap batch N+1's transfer with step N's compute instead of paying it
    at dispatch (the reference relied on Keras' implicit feed; TPU input
    overlap must be explicit).
    """
    arrays = {
        "images": batch.images,
        "gt_boxes": batch.gt_boxes,
        "gt_labels": batch.gt_labels,
        "gt_mask": batch.gt_mask,
    }
    if mesh is None:
        return {k: jax.device_put(v) for k, v in arrays.items()}
    if SPACE_AXIS in mesh.axis_names:
        # 2-D spatial mesh: images additionally shard H over `space`
        # (train.step.make_train_step_spatial).
        shardings = spatial_batch_shardings(mesh)
    else:
        s = batch_sharding(mesh)
        shardings = {k: s for k in arrays}
    if jax.process_count() == 1:
        return {
            k: jax.device_put(v, shardings[k]) for k, v in arrays.items()
        }
    return {
        k: jax.make_array_from_process_local_data(shardings[k], v)
        for k, v in arrays.items()
    }


def _prefetch_to_device(
    batches: Iterable[Batch], mesh: Mesh | None, depth: int = 2
) -> Iterator[tuple[tuple[int, ...], np.ndarray, dict[str, Any]]]:
    """Yield (images_shape, image_ids, device_batch), ``depth`` ahead.

    ``image_ids`` is the HOST copy of the batch's source ids — the
    numerics provenance dump records which images fed a tripped step
    (the device batch deliberately carries no ids).

    Double-buffered device prefetch (the standard ``prefetch_to_device``
    idiom): a background thread pulls host batches and calls
    ``_device_batch`` — which enqueues the host→device DMA — up to ``depth``
    batches ahead of the training step, so step k's compute overlaps both
    batch k+1's transfer and the host side of producing it.  The thread /
    bounded-queue / stop / error skeleton is the shared ``prefetch_map``
    (data/prefetch.py) — the eval fast path (evaluate/detect.py) runs the
    same machinery with a different transfer.

    ``depth <= 0`` degrades to synchronous in-line transfer (debugging).
    The generator's ``close()`` stops the thread; exceptions from the
    pipeline (e.g. a crashed decode worker) are re-raised here.
    """
    return prefetch_map(
        batches,
        lambda batch: (
            batch.images.shape,
            batch.image_ids,
            _device_batch(batch, mesh),
        ),
        depth=depth,
        thread_name="device-prefetch",
    )


class _AsyncEvalRunner:
    """Run the mid-training eval hook in a background thread on a
    snapshotted state, so the step stream keeps dispatching while the
    (host-heavy) eval runs: pipeline decode, detection post-processing and
    COCO scoring all happen off the loop's critical path, and the device
    interleaves eval detect programs between train steps instead of the
    host serializing a full eval pass into the step cadence.

    The "where safe" contract (LoopConfig.async_eval):

    - **Single-process only.**  A background thread issuing COLLECTIVES
      (the sharded eval's host all-gather, evaluate/detect.py) concurrently
      with the step stream can interleave differently across processes and
      deadlock the world; ``run_training`` falls back to synchronous eval
      (with a warning) when ``jax.process_count() > 1``.
    - **Snapshot, because the step donates.**  ``make_train_step`` donates
      its input state, so the thread cannot hold a reference into the live
      training state; the snapshot deep-copies params/batch_stats/step on
      device (async dispatch, enqueued before the next step's donation —
      the runtime orders the copy ahead of the donor) and DROPS opt_state:
      detection never reads it, and copying optimizer slots would double
      the snapshot memory for nothing.  Eval hooks used in async mode must
      therefore tolerate ``state.opt_state == ()`` (the in-tree hook does —
      the sharded branch already strips it).

    At most ONE eval is in flight: a new trigger joins the previous run
    first, so eval cadence provides natural backpressure instead of
    unbounded stacking.  Exceptions from the hook re-raise in the loop at
    the next drain/join; completed (step, metrics) pairs are logged from
    the LOOP thread (the JSONL logger is not locked for cross-thread
    appends).
    """

    def __init__(self, eval_fn, logger) -> None:
        self._eval_fn = eval_fn
        self._logger = logger
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._done: list[tuple[int, dict]] = []
        self._lock = threading.Lock()

    def launch(self, state, step: int) -> None:
        import jax.numpy as jnp

        self.join()  # one in flight; also surfaces a prior failure
        # Drop opt_state AND comm_state (ISSUE 13 EF residuals): eval
        # reads neither, and the residuals are data-axis-sharded.
        snapshot = jax.tree.map(
            jnp.copy, state.replace(opt_state=(), comm_state=())
        )

        def run() -> None:
            # Registered but immediately idle: a mid-training eval is
            # minutes of legitimate silence, and its LIVENESS is witnessed
            # by the components the eval itself spins up (eval-device-
            # prefetch, eval-consumer, the val pipeline's producer) — a
            # wedged eval shows up as THEIR stall, correctly attributed.
            hb = watchdog.register("async-eval")
            hb.idle()
            try:
                with trace.span("async_eval", step=step):
                    metrics = self._eval_fn(snapshot)
                with self._lock:
                    self._done.append((step, metrics))
            except BaseException as exc:  # surfaced at the next drain/join
                self._error = exc
            finally:
                hb.close()

        # watchdog: registers in run() at thread start.
        self._thread = threading.Thread(
            target=run, daemon=True, name="async-eval"
        )
        self._thread.start()

    def drain(self) -> None:
        """Log completed evals (loop thread); re-raise a failed one."""
        if self._error is not None:
            error, self._error = self._error, None
            raise RuntimeError("async eval hook failed") from error
        with self._lock:
            done, self._done = self._done, []
        for step, metrics in done:
            self._logger.log(step, metrics, prefix="eval")

    def join(self) -> None:
        """Wait for the in-flight eval (if any), then drain."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.drain()

    def finalize_on_error(self) -> None:
        """Unwind path: the loop is already propagating another exception.
        Join the in-flight eval (so its pipelines/threads are reclaimed
        before the process state is inspected) and log what completed, but
        WARN instead of raising — a failed eval must not mask the original
        error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        try:
            self.drain()
        except Exception as exc:
            warnings.warn(f"async eval failed during loop unwind: {exc!r}")


def _step_cost_flops(step_fn, state, device_arrays) -> float | None:
    """XLA-counted FLOPs of one train step, from the UNOPTIMIZED lowering
    (``Lowered.cost_analysis`` — tracing cost only, no second backend
    compile).  Feeds the ``cost_analysis`` trace instant + compile event
    the perf doctor's MFU/roofline estimate reads (obs/analyze), so the
    number exists per RUN, not only per bench.  None when the step
    wrapper has no AOT surface or the backend offers no cost analysis —
    the report then carries ``mfu: null`` instead of a guess."""
    lower = getattr(step_fn, "lower", None)
    if lower is None:
        return None
    try:
        cost = lower(state, device_arrays).cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else None
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
    except Exception:
        return None
    return flops if flops > 0 else None


def _compile_barrier(step_fn, state, device_arrays, hw) -> None:
    """Compile the step, then barrier at the COORDINATION SERVICE before
    its first execution on multi-process runs.

    XLA:CPU's Gloo collectives carry a hardcoded ~30 s receive timeout,
    and TPU collectives have finite timeouts too — while a cold step
    compile takes minutes.  Without this, the first process to finish
    compiling enters the step's collectives and times out waiting for
    peers still compiling (observed as deterministic-looking
    Gloo ReduceScatter failures in the 2-process ZeRO world, round 3).
    The coordination-service barrier (gRPC, 10 min budget) holds everyone
    until every process has COMPILED; execution then starts aligned.

    Error policy: a genuine compile failure PROPAGATES (the step would
    fail at dispatch anyway, and a swallowed compile error would defeat
    the barrier — the healthy peers would time out in collectives while
    this process died later with a confusing secondary error).  Only the
    genuinely optional pieces degrade to a skip: a step wrapper without
    the AOT ``lower`` surface, the private ``jax._src.distributed``
    module moving across JAX versions, or no distributed client (world
    brought up outside ``jax.distributed.initialize``).

    Bucket-order assumption: the barrier name is derived from the (H, W)
    bucket, so every process must reach new buckets in the same order.
    That holds by construction here — the global batch is assembled from
    aligned per-process shards of one global stream, so every process
    sees the same bucket at the same step index.  A custom per-process
    pipeline that broke this would park processes at differently-named
    barriers until the 10-minute budget expires (a loud, attributable
    failure rather than a silent data skew).
    """
    if jax.process_count() <= 1:
        return
    lower = getattr(step_fn, "lower", None)
    if lower is None:
        return  # no AOT surface: first dispatch compiles (and may skew)
    lower(state, device_arrays).compile()  # compile errors propagate
    try:
        # Private module; narrow the except to exactly the "JAX moved it"
        # failure so real errors (including barrier timeout) still raise.
        from jax._src import distributed
    except ImportError as e:  # pragma: no cover - version-specific
        warnings.warn(f"compile barrier skipped: {e!r}")
        return
    client = getattr(
        getattr(distributed, "global_state", None), "client", None
    )
    if client is None:
        return  # no coordination service (external world bring-up)
    client.wait_at_barrier(f"train_step_compiled_{hw[0]}x{hw[1]}", 600_000)


def run_training(
    model,
    state: TrainState,
    batches: Iterable[Batch],
    num_classes: int,
    config: LoopConfig,
    mesh: Mesh | None = None,
    loss_config: losses_lib.LossConfig = losses_lib.LossConfig(),
    matching_config: matching_lib.MatchingConfig = matching_lib.MatchingConfig(),
    anchor_config=None,
    schedule: Callable[[int], float] | None = None,
    eval_fn: Callable[[TrainState], dict[str, float]] | None = None,
    logger: MetricLogger | None = None,
    shard_weight_update: bool = False,
    quantized_allreduce: bool = False,
    comm=None,
    topology=None,
    allow_data_axis_divergence: bool = False,
) -> TrainState:
    """Run ``config.total_steps`` of SPMD training; returns the final state.

    ``eval_fn(state) -> metrics`` is the CocoEval-callback equivalent, called
    every ``eval_every`` steps and at the end.  One train step is compiled
    per (H, W) shape bucket seen in the stream.

    ``comm`` (a ``comm.CommConfig``, ISSUE 13) selects the gradient-
    communication policy — bucketed int8/bf16 compression with error
    feedback, optional backward overlap; composes with
    ``shard_weight_update`` (the compression moves to the ZeRO update
    gather).  ``quantized_allreduce`` is the deprecated bool alias.
    ``topology`` (a ``parallel.mesh.CommTopology``, ISSUE 16) makes the
    comm collective hierarchical — exact within each ICI slice,
    compressed only on the cross-slice DCN hop (train/step.py).

    A 2-D mesh carrying a ``space`` axis selects the spatially partitioned
    step (image-H sharding; train/step.py::make_train_step_spatial) —
    exclusive with the ZeRO and comm-compression flavors.
    """
    spatial = mesh is not None and SPACE_AXIS in mesh.axis_names
    comm_on = comm is not None and getattr(comm, "enabled", False)
    if spatial and (shard_weight_update or quantized_allreduce or comm_on):
        raise ValueError(
            "spatial partitioning is exclusive with --shard-weight-update "
            "and --comm-compress/--quantized-allreduce"
        )
    logger = logger or MetricLogger(log_dir=None)
    ckpt = None
    if config.checkpoint_every and config.checkpoint_dir:
        ckpt = CheckpointManager(
            config.checkpoint_dir,
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.checkpoint_every,
            metadata=config.ckpt_metadata,
            sink=logger,
        )
        if config.resume and ckpt.latest_step() is not None:
            t_restore = monotonic_s()
            try:
                with trace.span("ckpt_restore"):
                    state = ckpt.restore(state)
            except Exception as e:
                raise RuntimeError(
                    f"restoring {config.checkpoint_dir} failed (root cause "
                    "in the chained traceback). Optimizer-state layouts "
                    "reshard automatically across world sizes and between "
                    "--shard-weight-update and replicated mode "
                    "(utils/checkpoint.py), so a shape mismatch here means "
                    "a DIFFERENT model/optimizer was checkpointed; "
                    "otherwise every checkpoint in the directory is torn — "
                    "see ckpt_torn_skipped on stderr, or start fresh with "
                    "--no-resume."
                ) from e
            print(f"resumed from step {int(state.step)}", flush=True)
            restore_s = monotonic_s() - t_restore
            log_event = getattr(logger, "event", None)
            if log_event is not None:
                log_event(
                    "ckpt_restored",
                    step=int(state.step),
                    restore_s=round(restore_s, 4),
                )
            if jax.process_count() == 1:
                # Restored leaves are HOST numpy.  Materialize jax-OWNED
                # device buffers via a compiled copy (jnp.copy), never a
                # bare device_put: XLA:CPU's device_put is ZERO-COPY for
                # numpy inputs, the train step DONATES its input state,
                # and donating a numpy-aliased buffer hands numpy-owned
                # memory to XLA's allocator — observed as glibc heap
                # corruption ("corrupted double-linked list") at the
                # first post-resume step.  The mesh replication below
                # then proceeds from committed device arrays, exactly as
                # it always has.  Multi-host keeps host numpy: every
                # process restored identical values and the replication
                # block's global device_put wants process-local host
                # data (TPU puts always copy; the alias hazard is
                # CPU-backend-only).
                import jax.numpy as jnp

                state = jax.tree.map(jnp.copy, state)

    if mesh is not None:
        # Replicate state over the mesh (restored arrays land committed to a
        # single device, which conflicts with the shard_map'd step).  In
        # weight-update-sharded mode the opt_state leaves keep their 1/N
        # layout on the data axis instead (parallel/zero.py storage format).
        def _place_comm_state(comm_state):
            # Comm EF residuals (ISSUE 13) keep their 1/N data-axis
            # layout, exactly like ZeRO optimizer state.
            from jax.sharding import NamedSharding

            from batchai_retinanet_horovod_coco_tpu.comm.compress import (
                state_partition_specs,
            )

            return jax.tree.map(
                lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
                comm_state,
                state_partition_specs(comm_state),
            )

        if shard_weight_update:
            from jax.sharding import NamedSharding

            from batchai_retinanet_horovod_coco_tpu.parallel.zero import (
                opt_state_partition_specs,
            )

            rep = replicated_sharding(mesh)
            opt_state = jax.tree.map(
                lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
                state.opt_state,
                opt_state_partition_specs(state.opt_state),
            )
            state = state.replace(
                step=jax.device_put(state.step, rep),
                params=jax.device_put(state.params, rep),
                batch_stats=jax.device_put(state.batch_stats, rep),
                opt_state=opt_state,
                comm_state=_place_comm_state(state.comm_state),
            )
        elif getattr(state, "comm_state", ()):
            rep = replicated_sharding(mesh)
            state = state.replace(
                step=jax.device_put(state.step, rep),
                params=jax.device_put(state.params, rep),
                batch_stats=jax.device_put(state.batch_stats, rep),
                opt_state=jax.device_put(state.opt_state, rep),
                comm_state=_place_comm_state(state.comm_state),
            )
        else:
            state = jax.device_put(state, replicated_sharding(mesh))

    if trace.enabled():
        # Run metadata INTO the trace (the perf doctor resolves device
        # peak TFLOP/s and process topology from artifacts alone — the
        # events JSONL may not exist for this run).
        try:
            trace.instant(
                "run_meta",
                device_kind=jax.devices()[0].device_kind,
                local_device_count=jax.local_device_count(),
                process_count=jax.process_count(),
            )
        except Exception:
            pass  # metadata must never block training bring-up

    step_fns: dict[tuple[int, int], Callable] = {}
    start_step = int(state.step)
    last_saved: int | None = None
    # Clamp the profile window into the steps this run will actually take
    # (otherwise short runs would never produce a trace).
    prof_start = min(
        max(config.profile_start_step, start_step + 1),
        max(start_step + 1, config.total_steps - config.profile_steps + 1),
    )
    prof_end = min(config.total_steps, prof_start + config.profile_steps - 1)
    window_t0 = monotonic_s()
    window_images = 0
    window_data_wait = 0.0  # host time blocked on the input pipeline
    window_steps = 0
    metrics = None
    eval_runner = None
    if config.async_eval and eval_fn is not None:
        if jax.process_count() > 1:
            warnings.warn(
                "async_eval requested in a multi-process world; falling "
                "back to synchronous eval (a background thread issuing the "
                "eval all-gather concurrently with step collectives can "
                "deadlock — see _AsyncEvalRunner)"
            )
        else:
            eval_runner = _AsyncEvalRunner(eval_fn, logger)
    # Numerics flight recorder: the in-step summary gate (compile-time —
    # the disabled step's program is unchanged) plus the always-armed
    # provenance context for a tripped finite-check.
    numerics_config = NumericsConfig(enabled=config.numerics)

    it = _prefetch_to_device(batches, mesh, config.device_prefetch)
    # The loop's own heartbeat: one beat per step.  Long legitimate gaps
    # (sync eval, final epilogue) are bracketed with idle() so only a
    # genuinely wedged step stream — or the data stall it is blocked on —
    # trips the watchdog.  The details cell is HOST-side: the watchdog
    # thread must never touch ``state.step`` (a possibly-donated device
    # array).
    last_step = [start_step]
    loop_hb = watchdog.register(
        "train-loop", details=lambda: {"step": last_step[0]}
    )

    try:
        for step in range(start_step + 1, config.total_steps + 1):
            if eval_runner is not None:
                eval_runner.drain()  # log finished evals; surface failures
            loop_hb.beat()
            last_step[0] = step
            t_data = monotonic_s()
            with trace.span("data_wait"):
                images_shape, image_ids, device_arrays = next(it)
            window_data_wait += monotonic_s() - t_data
            window_steps += 1
            hw = images_shape[1:3]
            step_fn = step_fns.get(hw)
            if step_fn is None:
                # AOT point: build + (multi-process) compile-and-barrier.
                # The span/event turn each bucket's one-time multi-minute
                # gap into an attributed compile, not an apparent stall —
                # and the heartbeat goes idle for the same reason (a cold
                # flagship compile is minutes, far past any stall budget).
                loop_hb.idle()
                t_compile = monotonic_s()
                with trace.span(
                    "compile_train_step", bucket=f"{hw[0]}x{hw[1]}"
                ):
                    if spatial:
                        step_fn = step_fns[hw] = make_train_step_spatial(
                            model,
                            hw,
                            num_classes,
                            mesh=mesh,
                            loss_config=loss_config,
                            matching_config=matching_config,
                            anchor_config=anchor_config,
                            allow_data_axis_divergence=allow_data_axis_divergence,
                            numerics=numerics_config,
                        )
                    else:
                        step_fn = step_fns[hw] = make_train_step(
                            model,
                            hw,
                            num_classes,
                            mesh=mesh,
                            loss_config=loss_config,
                            matching_config=matching_config,
                            anchor_config=anchor_config,
                            shard_weight_update=shard_weight_update,
                            quantized_allreduce=quantized_allreduce,
                            comm=comm,
                            topology=topology,
                            numerics=numerics_config,
                        )
                    # No process may enter the step's collectives while a
                    # peer is still compiling (collective timeouts <<
                    # compile times).
                    _compile_barrier(step_fn, state, device_arrays, hw)
                    # Obs runs also record the step's XLA-counted FLOPs
                    # (one extra trace of the step, no extra compile) so
                    # PERF_REPORT.json can carry an MFU estimate.
                    flops = (
                        _step_cost_flops(step_fn, state, device_arrays)
                        if trace.enabled()
                        else None
                    )
                    if flops is not None:
                        trace.instant(
                            "cost_analysis",
                            target="train_step",
                            bucket=f"{hw[0]}x{hw[1]}",
                            flops=flops,
                            batch=int(images_shape[0]),
                        )
                loop_hb.beat()
                # Live-telemetry record site (one bool check while off):
                # the status server's train_compiles_total/last_compile.
                telemetry.record_compile(
                    f"{hw[0]}x{hw[1]}", monotonic_s() - t_compile
                )
                # Duck-typed: tests pass bare .log-only logger fakes.
                log_event = getattr(logger, "event", None)
                if log_event is not None:
                    log_event(
                        "compile",
                        target="train_step",
                        bucket=f"{hw[0]}x{hw[1]}",
                        step=step,
                        build_s=round(monotonic_s() - t_compile, 3),
                        flops=flops,
                    )
            if config.profile_dir and step == prof_start:
                jax.profiler.start_trace(config.profile_dir)
            with trace.span("step"):
                state, metrics = step_fn(state, device_arrays)
            if config.profile_dir and step == prof_end:
                jax.block_until_ready(metrics)
                jax.profiler.stop_trace()
            # Global batch size = local batch × process_count (each process
            # feeds its shard of the global batch).
            window_images += images_shape[0] * (
                jax.process_count() if mesh is not None else 1
            )

            # ``step`` is tracked host-side (state.step mirrors it) so the loop
            # never forces a per-step device sync on tunneled TPU backends; the
            # finiteness sanitizer therefore runs at a bounded cadence — every
            # log window, every _FINITE_CHECK_EVERY steps when log_every=0, and
            # unconditionally before any checkpoint save (a NaN-poisoned state
            # must never reach disk: auto-resume would restore the poison and
            # make recovery impossible without --no-resume).
            is_log = (
                config.log_every and step % config.log_every == 0
            ) or step == config.total_steps
            will_save = ckpt is not None and ckpt.should_save(step)
            check_every = config.log_every or _FINITE_CHECK_EVERY
            cadence = (
                f"every {check_every} steps and before each checkpoint save"
            )
            # Both check sites — the bounded cadence check and the
            # pre-save poisoned-state gate (``will_save``) — go through
            # ONE finite helper (obs/numerics.first_nonfinite_scalar) and
            # one abort path (provenance dump + raise); test_numerics
            # pins both.
            if not is_log and (will_save or step % check_every == 0):
                sentinels = {
                    name: jax.device_get(metrics[name])
                    for name in _SENTINEL_METRICS
                    if name in metrics
                }
                hit = numerics_lib.first_nonfinite_scalar(sentinels)
                if hit is not None:
                    _abort_nonfinite(
                        hit[0], hit[1], step, cadence,
                        model=model, state=state,
                        device_arrays=device_arrays, image_ids=image_ids,
                        metrics=metrics, rng_seed=config.rng_seed,
                        dump_dir=config.numerics_dump_dir, logger=logger,
                    )

            if is_log:
                with trace.span("metrics_fetch"):
                    scalars = {
                        k: v for k, v in jax.device_get(metrics).items()
                    }
                hit = numerics_lib.first_nonfinite_scalar(
                    {k: scalars[k] for k in _SENTINEL_METRICS if k in scalars}
                )
                if hit is not None:
                    _abort_nonfinite(
                        hit[0], hit[1], step, cadence,
                        model=model, state=state,
                        device_arrays=device_arrays, image_ids=image_ids,
                        metrics=metrics, rng_seed=config.rng_seed,
                        dump_dir=config.numerics_dump_dir, logger=logger,
                    )
                dt = monotonic_s() - window_t0
                scalars["images_per_sec"] = window_images / max(dt, 1e-9)
                # Step-time breakdown (SURVEY.md §5.5): how much of the step the
                # host spent BLOCKED on the input pipeline — the classic
                # detection scaling-efficiency killer (SURVEY.md §7.3 part 6).
                scalars["step_time_ms"] = dt / max(window_steps, 1) * 1e3
                scalars["data_wait_ms"] = (
                    window_data_wait / max(window_steps, 1) * 1e3
                )
                # Cumulative gt boxes dropped by max_gt padding (pipeline
                # counter) — silent truncation poisons targets, so it is a
                # first-class metric whenever it is nonzero.
                pipe_stats = getattr(batches, "stats", None)
                if pipe_stats is not None and pipe_stats.truncated_boxes:
                    scalars["truncated_gt_boxes"] = pipe_stats.truncated_boxes
                if schedule is not None:
                    scalars["lr"] = float(schedule(step - 1))
                    scale = optim.plateau_scale(state.opt_state)
                    if scale is not None:
                        scalars["lr"] *= scale  # data-driven ReduceLROnPlateau
                logger.log(step, scalars)
                # Live-telemetry record site (one bool check while off):
                # step rate / step time / data-wait fraction for the
                # --obs-port status server and the SLO monitor's rules.
                telemetry.record_train_window(
                    step=step,
                    images_per_s=scalars["images_per_sec"],
                    step_time_ms=scalars["step_time_ms"],
                    data_wait_ms=scalars["data_wait_ms"],
                )
                # Numerics record sites (ISSUE 10; each one bool check
                # while its plane is off): the grad_norm/update_ratio/
                # nonfinite gauges feed the SLO monitor's built-in
                # nonfinite + grad-norm-spike rules whenever telemetry
                # is live; the dedicated structured JSONL record (the
                # perf doctor's numerics section) exists only when the
                # in-step summary is on.
                telemetry.record_numerics(
                    grad_norm=scalars.get(numerics_lib.GRAD_NORM),
                    update_ratio=scalars.get(numerics_lib.UPDATE_RATIO),
                    nonfinite=scalars.get(numerics_lib.NONFINITE),
                    replica_agreement=scalars.get(
                        numerics_lib.REPLICA_AGREEMENT
                    ),
                )
                # Comm/EF health record site (ISSUE 13; one bool check
                # while telemetry is off, absent keys skipped): feeds
                # the train_ef_residual/saturation gauges the always-
                # armed ef_residual_spike SLO rule watches, plus the
                # cumulative bytes-on-wire counter.
                telemetry.record_comm(
                    ef_residual=scalars.get(numerics_lib.EF_RESIDUAL),
                    ef_saturation=scalars.get(numerics_lib.EF_SATURATION),
                    compressed_bytes=scalars.get(numerics_lib.COMM_BYTES),
                    # Per-hop plane (ISSUE 16): present only on
                    # hierarchical runs — ICI/DCN byte counters plus the
                    # DCN-labeled residual gauge the per-hop
                    # ef_residual_spike rule watches.
                    ici_bytes=scalars.get(numerics_lib.COMM_ICI_BYTES),
                    dcn_bytes=scalars.get(numerics_lib.COMM_DCN_BYTES),
                    ef_residual_dcn=scalars.get(
                        numerics_lib.EF_RESIDUAL_DCN
                    ),
                    steps=window_steps,
                )
                if config.numerics:
                    num_keys = numerics_lib.numerics_metric_keys(scalars)
                    log_event = getattr(logger, "event", None)
                    if log_event is not None and num_keys:
                        log_event(
                            "numerics",
                            step=step,
                            **{k: float(scalars[k]) for k in num_keys},
                        )
                if trace.enabled():
                    # Device HBM occupancy as Chrome counter tracks, once
                    # per log window (memory_stats() is a host call; CPU
                    # backends report nothing and this is a no-op).
                    for name, value in device_memory_stats():
                        trace.counter(name, value)
                window_t0 = monotonic_s()
                window_images = 0
                window_data_wait = 0.0
                window_steps = 0

            if will_save and ckpt.save(state, step=step):
                last_saved = step

            if (
                eval_fn is not None
                and config.eval_every
                and step % config.eval_every == 0
                and step < config.total_steps
            ):
                if eval_runner is not None:
                    # Usually non-blocking: the hook runs on a snapshotted
                    # copy while the step stream continues.  No window
                    # reset — the steps keep flowing (the eval's device
                    # work shows up honestly as slightly slower steps, not
                    # as a gap).  BUT launch() first joins a still-running
                    # previous eval (one in flight max), which can block
                    # for minutes when eval_every < eval duration — idle
                    # the loop heartbeat across it, as the sync branch
                    # below does.
                    loop_hb.idle()
                    eval_runner.launch(state, step)
                    loop_hb.beat()
                else:
                    # Synchronous eval: minutes of legitimate step-stream
                    # silence — idle the loop heartbeat (the eval's own
                    # components carry liveness) and re-arm after.
                    loop_hb.idle()
                    with trace.span("eval", step=step):
                        eval_metrics = eval_fn(state)
                    loop_hb.beat()
                    logger.log(step, eval_metrics, prefix="eval")
                    # Eval time must not pollute the next window's
                    # step-time metrics.
                    window_t0 = monotonic_s()
                    window_images = 0
                    window_data_wait = 0.0
                    window_steps = 0

    except BaseException:
        # Exception exit: reap the in-flight async eval during unwind (its
        # error/metrics are warned/logged, never raised — they must not
        # mask the original exception).  An explicit except, not a
        # sys.exc_info() probe in the finally — exc_info is thread-wide
        # and would misfire when run_training is itself called inside a
        # caller's except block.  The normal path joins below, where eval
        # failures DO raise.
        if eval_runner is not None:
            eval_runner.finalize_on_error()
        if ckpt is not None:
            # Quiesce the async writer BEFORE the exception escapes: an
            # --auto-resume caller re-enters with a NEW manager on the
            # same directory, and an abandoned in-flight write racing it
            # could gc the new writer's tmp dir or publish a pre-abort
            # state after the heal chose its restore point.  close()
            # joins the in-flight save (a healthy, pre-abort checkpoint
            # — letting it land is exactly right); its own failure is
            # warned, never raised — it must not mask the original
            # exception.
            try:
                ckpt.close()
            except Exception as ckpt_exc:
                warnings.warn(
                    "checkpoint writer close failed during loop unwind: "
                    f"{ckpt_exc!r}"
                )
        raise
    finally:
        # Stop the prefetch thread deterministically (even when the
        # loop exits via an exception) before eval/checkpoint epilogue.
        it.close()
        # The step stream is over; the epilogue (final eval, checkpoint
        # flush) has its own components/timeouts.
        loop_hb.close()

    final_step = max(start_step, config.total_steps)
    if eval_runner is not None:
        # The final eval below is synchronous; finish (and log, in step
        # order) any still-running mid-run eval first.
        eval_runner.join()
    if eval_fn is not None:
        with trace.span("final_eval", step=final_step):
            final_metrics = eval_fn(state)
        logger.log(final_step, final_metrics, prefix="eval")
    if ckpt is not None:
        if last_saved != final_step:
            ckpt.save(state, step=final_step, force=True)
        ckpt.close()
    return state
