"""Optimizer + LR schedule factory.

Reference training hyper-surface (SURVEY.md M11/W1): Adam at a small base LR
scaled by ``hvd.size()`` (linear-scaling rule), ReduceLROnPlateau, optional
``--freeze-backbone``.  TPU-native redesign: everything is an optax chain
built ONCE — the schedule is a pure function of the step (compiled into the
train step; no callback machinery), warmup replaces the Horovod
LearningRateWarmup callback, and backbone freezing is a gradient mask rather
than layer.trainable flips (no graph rebuild).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import optax


def clip_by_global_norm_precomputed(
    max_norm: float,
) -> optax.GradientTransformationExtraArgs:
    """``optax.clip_by_global_norm`` that can REUSE a precomputed norm.

    The train step already computes ``optax.global_norm(grads)`` for its
    ``grad_norm`` metric (SURVEY.md §5.5); the stock optax clip then
    recomputed the identical reduction inside the chain.  This transform
    accepts the step's value via extra args (``grad_norm=...``, forwarded
    by ``optax.chain``/``multi_transform`` — TrainState.apply_gradients
    passes it), so the metric and the clip share ONE reduction, and the
    recorded pre-clip norm is BY CONSTRUCTION the norm the clip acted on
    (the numerics plane's contract, obs/numerics.py).  Without the extra
    arg it computes the norm itself — identical semantics either way
    (``scale = max_norm / max(norm, max_norm)``, the same rule as
    ``clip_by_global_norm_sharded``; equivalence pinned by
    tests/unit/test_numerics.py).

    NOT safe under ``optax.multi_transform`` masking: the masked branch
    sees only its subtree's updates, while the step's precomputed norm
    covers the FULL tree — forwarding it would clip trained params by a
    norm inflated with frozen gradients (a ~200x effective-LR collapse
    in a freeze-backbone run with large frozen grads).  ``make_optimizer``
    therefore keeps the stock self-computing clip whenever
    ``freeze_backbone`` masks the chain (pinned by test_numerics).
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None, *, grad_norm=None, **extra):
        del params, extra
        norm = optax.global_norm(updates) if grad_norm is None else grad_norm
        scale = max_norm / jnp.maximum(norm, max_norm)
        return jax.tree.map(lambda u: u * scale, updates), state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    optimizer: str = "sgd"  # "sgd" | "adam"
    base_lr: float = 0.01  # per-256-global-batch for sgd (detectron rule)
    # Linear-scaling rule: effective lr = base_lr * global_batch / 256 for
    # sgd, or base_lr * world_size for adam (the reference's hvd.size() rule).
    global_batch_size: int = 16
    world_size: int = 1
    warmup_steps: int = 500
    total_steps: int = 90_000
    schedule: str = "multistep"  # "multistep" | "cosine" | "constant" | "plateau"
    # Multistep: decay 10x at these fractions of total_steps (detectron 1x).
    milestones: tuple[float, ...] = (2 / 3, 8 / 9)
    # "plateau": the reference's ReduceLROnPlateau (keras-retinanet monitors
    # per-epoch training loss, factor 0.1, patience 2).  TPU-native redesign:
    # no callback — optax.contrib.reduce_on_plateau rides INSIDE the compiled
    # step, fed the pmean-ed loss, so every replica scales identically and
    # the controller state checkpoints/restores with the rest of opt_state.
    # ``plateau_window`` steps of loss are averaged per comparison (the epoch
    # analogue); patience counts windows.
    plateau_factor: float = 0.1
    plateau_patience: int = 2
    plateau_window: int = 1000
    plateau_min_delta: float = 1e-4
    momentum: float = 0.9
    weight_decay: float = 1e-4
    clip_global_norm: float = 10.0
    freeze_backbone: bool = False


def peak_lr(config: OptimizerConfig) -> float:
    if config.optimizer == "adam":
        return config.base_lr * config.world_size
    return config.base_lr * config.global_batch_size / 256.0


def make_schedule(config: OptimizerConfig) -> optax.Schedule:
    peak = peak_lr(config)
    # join_schedules rebases the post-warmup schedule to step 0 at the join,
    # so boundaries/horizons are expressed relative to the end of warmup —
    # milestones land at the intended GLOBAL step.
    if config.schedule in ("constant", "plateau"):
        # plateau: base LR is flat; the reduce_on_plateau transform in
        # make_optimizer supplies the data-driven decay.
        sched = optax.constant_schedule(peak)
    elif config.schedule == "cosine":
        sched = optax.cosine_decay_schedule(
            peak, max(1, config.total_steps - config.warmup_steps)
        )
    elif config.schedule == "multistep":
        boundaries = {
            int(m * config.total_steps) - config.warmup_steps: 0.1
            for m in config.milestones
        }
        sched = optax.piecewise_constant_schedule(peak, boundaries)
    else:
        raise ValueError(f"unknown schedule: {config.schedule!r}")
    if config.warmup_steps > 0:
        warmup = optax.linear_schedule(
            peak / max(1, config.warmup_steps), peak, config.warmup_steps
        )
        return optax.join_schedules([warmup, sched], [config.warmup_steps])
    return sched


def make_optimizer(
    config: OptimizerConfig,
    shard_clip_axis: str | None = None,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """(transform, schedule) — schedule returned separately for logging.

    ``shard_clip_axis``: name of the mesh axis the updates are sharded over
    (weight-update-sharded mode, parallel/zero.py).  The chain then uses
    ``clip_by_global_norm_sharded`` — same clip rule, norm psum-ed across
    shards — in the SAME chain position, so freeze-masking applies to it
    identically.  The clip value has exactly one source: this config.
    """
    schedule = make_schedule(config)
    if config.optimizer == "sgd":
        core = optax.chain(
            optax.add_decayed_weights(config.weight_decay),
            optax.sgd(schedule, momentum=config.momentum),
        )
    elif config.optimizer == "adam":
        core = optax.adam(schedule)
    else:
        raise ValueError(f"unknown optimizer: {config.optimizer!r}")

    # The freeze-masked chain must NOT consume the step's precomputed
    # norm: inside multi_transform the clip sees only the trained
    # subtree, and the full-tree norm (which includes the frozen
    # backbone's gradients) would silently over-clip it — see
    # clip_by_global_norm_precomputed's docstring.  The frozen chain
    # keeps the self-computing clips (extra args are dropped for plain
    # transforms, so the step's grad_norm= is harmlessly ignored).
    use_precomputed = not config.freeze_backbone
    if shard_clip_axis is not None:
        from batchai_retinanet_horovod_coco_tpu.parallel.zero import (
            clip_by_global_norm_sharded,
        )

        clip = clip_by_global_norm_sharded(
            config.clip_global_norm, shard_clip_axis,
            use_precomputed=use_precomputed,
        )
    elif use_precomputed:
        # Accepts the step's precomputed global norm via extra args so the
        # grad_norm metric and the clip share one reduction (identical
        # semantics to optax.clip_by_global_norm otherwise).
        clip = clip_by_global_norm_precomputed(config.clip_global_norm)
    else:
        clip = optax.clip_by_global_norm(config.clip_global_norm)
    tx = optax.chain(clip, core)

    if config.freeze_backbone:
        # Zero gradients for the backbone subtree (reference --freeze-backbone).
        def label(params):
            return {
                k: ("frozen" if k == "backbone" else "trained") for k in params
            }

        tx = optax.multi_transform(
            {"trained": tx, "frozen": optax.set_to_zero()}, label
        )

    if config.schedule == "plateau":
        # Appended last so the scale multiplies the whole update (= scaling
        # the LR).  The step feeds it value=loss via apply_gradients.
        tx = optax.chain(
            tx,
            optax.contrib.reduce_on_plateau(
                factor=config.plateau_factor,
                patience=config.plateau_patience,
                # rtol=0: improvement is judged against the ABSOLUTE
                # min_delta (keras ReduceLROnPlateau semantics), not optax's
                # default best_value-relative threshold.  optax rejects
                # rtol == atol == 0, so min_delta=0 (legal in keras) is
                # floored at a value far below any f32 loss resolution.
                rtol=0.0,
                atol=max(config.plateau_min_delta, 1e-12),
                accumulation_size=config.plateau_window,
            ),
        )
    return optax.with_extra_args_support(tx), schedule


def plateau_scale(opt_state) -> float | None:
    """Current ReduceLROnPlateau LR scale in ``opt_state`` (None if absent).

    Matches the controller's state node by type — a name-based search
    ("scale") collides with fields of other optax states in the chain.
    """
    plateau_state = optax.contrib.ReduceLROnPlateauState
    found = [
        x
        for x in jax.tree.leaves(
            opt_state, is_leaf=lambda x: isinstance(x, plateau_state)
        )
        if isinstance(x, plateau_state)
    ]
    return float(found[0].scale) if found else None
