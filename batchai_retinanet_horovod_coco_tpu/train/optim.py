"""Optimizer + LR schedule factory.

Reference training hyper-surface (SURVEY.md M11/W1): Adam at a small base LR
scaled by ``hvd.size()`` (linear-scaling rule), ReduceLROnPlateau, optional
``--freeze-backbone``.  TPU-native redesign: everything is an optax chain
built ONCE — the schedule is a pure function of the step (compiled into the
train step; no callback machinery), warmup replaces the Horovod
LearningRateWarmup callback, and backbone freezing is a gradient mask rather
than layer.trainable flips (no graph rebuild).
"""

from __future__ import annotations

import dataclasses

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    optimizer: str = "sgd"  # "sgd" | "adam"
    base_lr: float = 0.01  # per-256-global-batch for sgd (detectron rule)
    # Linear-scaling rule: effective lr = base_lr * global_batch / 256 for
    # sgd, or base_lr * world_size for adam (the reference's hvd.size() rule).
    global_batch_size: int = 16
    world_size: int = 1
    warmup_steps: int = 500
    total_steps: int = 90_000
    schedule: str = "multistep"  # "multistep" | "cosine" | "constant"
    # Multistep: decay 10x at these fractions of total_steps (detectron 1x).
    milestones: tuple[float, ...] = (2 / 3, 8 / 9)
    momentum: float = 0.9
    weight_decay: float = 1e-4
    clip_global_norm: float = 10.0
    freeze_backbone: bool = False


def peak_lr(config: OptimizerConfig) -> float:
    if config.optimizer == "adam":
        return config.base_lr * config.world_size
    return config.base_lr * config.global_batch_size / 256.0


def make_schedule(config: OptimizerConfig) -> optax.Schedule:
    peak = peak_lr(config)
    # join_schedules rebases the post-warmup schedule to step 0 at the join,
    # so boundaries/horizons are expressed relative to the end of warmup —
    # milestones land at the intended GLOBAL step.
    if config.schedule == "constant":
        sched = optax.constant_schedule(peak)
    elif config.schedule == "cosine":
        sched = optax.cosine_decay_schedule(
            peak, max(1, config.total_steps - config.warmup_steps)
        )
    elif config.schedule == "multistep":
        boundaries = {
            int(m * config.total_steps) - config.warmup_steps: 0.1
            for m in config.milestones
        }
        sched = optax.piecewise_constant_schedule(peak, boundaries)
    else:
        raise ValueError(f"unknown schedule: {config.schedule!r}")
    if config.warmup_steps > 0:
        warmup = optax.linear_schedule(
            peak / max(1, config.warmup_steps), peak, config.warmup_steps
        )
        return optax.join_schedules([warmup, sched], [config.warmup_steps])
    return sched


def make_optimizer(
    config: OptimizerConfig,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """(transform, schedule) — schedule returned separately for logging."""
    schedule = make_schedule(config)
    if config.optimizer == "sgd":
        core = optax.chain(
            optax.add_decayed_weights(config.weight_decay),
            optax.sgd(schedule, momentum=config.momentum),
        )
    elif config.optimizer == "adam":
        core = optax.adam(schedule)
    else:
        raise ValueError(f"unknown optimizer: {config.optimizer!r}")

    parts = [optax.clip_by_global_norm(config.clip_global_norm), core]
    tx = optax.chain(*parts)

    if config.freeze_backbone:
        # Zero gradients for the backbone subtree (reference --freeze-backbone).
        def label(params):
            return {
                k: ("frozen" if k == "backbone" else "trained") for k in params
            }

        tx = optax.multi_transform(
            {"trained": tx, "frozen": optax.set_to_zero()}, label
        )
    return tx, schedule
