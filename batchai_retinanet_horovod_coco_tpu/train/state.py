"""Train state pytree: params, optional BN stats, optimizer state, step.

The reference kept this state implicit inside Keras/Horovod (SURVEY.md L2/H1:
model weights + replicated optimizer slots, synced by broadcast at start).
Here it is an explicit pytree, so sharding it (replicated today; optionally
optimizer-state-sharded over the data axis later, SURVEY.md §2.4 ZeRO row) is
a matter of NamedSharding annotations, and checkpointing is orbax on the
whole pytree (SURVEY.md §5.4).

Initial-weight sync across hosts is free by construction: every process
builds params from the same PRNG key, so there is no broadcast step (the
reference needed ``hvd.broadcast_global_variables``, SURVEY.md H1).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


def model_variables(state: "TrainState") -> dict[str, Any]:
    """Flax variables dict for ``model.apply`` from a TrainState.

    The single place that knows which variable collections exist; forward
    paths (train step, eval forward, detection) all assemble through here so
    a new collection (e.g. EMA params) propagates everywhere at once.
    """
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    return variables


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    batch_stats: Any  # empty dict for GN models
    opt_state: Any
    # Static (non-pytree) fields:
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    # Comm subsystem state (ISSUE 13): gradient-compression error-feedback
    # residuals, keyed per bucket (DP) or per leaf (ZeRO) — flat arrays
    # sharded over the data axis like ZeRO optimizer state, and
    # checkpointed/resharded the same way (comm/compress.py).  Empty for
    # every run without compression, in which case it contributes no
    # pytree leaves and the compiled step is unchanged.
    comm_state: Any = ()

    def apply_gradients(
        self,
        grads: Any,
        new_batch_stats: Any | None = None,
        *,
        loss_value: jnp.ndarray | None = None,
        grad_norm: jnp.ndarray | None = None,
    ):
        """One optimizer update.

        ``loss_value`` (the replica-identical pmean-ed loss) is forwarded to
        extra-args transforms — optax.contrib.reduce_on_plateau consumes it
        as ``value`` (train/optim.py "plateau" schedule); plain transforms
        never see it.  ``grad_norm`` (the step's precomputed global norm)
        likewise reaches ``clip_by_global_norm_precomputed`` so the metric
        and the clip share one reduction (obs/numerics.py contract).
        """
        if isinstance(self.tx, optax.GradientTransformationExtraArgs) and (
            loss_value is not None or grad_norm is not None
        ):
            extra = {}
            if loss_value is not None:
                extra["value"] = loss_value
            if grad_norm is not None:
                extra["grad_norm"] = grad_norm
            updates, new_opt_state = self.tx.update(
                grads, self.opt_state, self.params, **extra
            )
        else:
            updates, new_opt_state = self.tx.update(
                grads, self.opt_state, self.params
            )
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            opt_state=new_opt_state,
        )


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    example_image_shape: tuple[int, int, int, int],
    rng: jax.Array,
    init_opt_state: bool = True,
) -> TrainState:
    """Initialize params; identical on every process (same PRNG key).

    ``model.init`` is wrapped in jit: eager init dispatches thousands of tiny
    ops, which is pathological on remote/tunneled TPU backends (measured
    ~4 min eager vs seconds jitted for ResNet-50).

    ``init_opt_state=False`` leaves ``opt_state`` empty: weight-update-
    sharded mode (parallel/zero.py) initializes its 1/N layout directly and
    must not pay the peak memory of a throwaway replicated ``tx.init``.
    """
    variables = jax.jit(model.init)(rng, jnp.zeros(example_image_shape, jnp.float32))
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params) if init_opt_state else (),
        tx=tx,
    )
