"""The SPMD train step: ONE jit-compiled program per shape bucket.

This is the TPU-native replacement for the reference's entire per-step stack
(SURVEY.md call stack 3.4): Keras ``train_function`` forward/backward +
``hvd.DistributedOptimizer``'s per-tensor NCCL ring allreduce.  Here the
whole thing — forward, on-device target assignment, losses, backward,
``lax.pmean`` gradient allreduce over the ``data`` mesh axis, and the
optimizer update — is one XLA program built with ``shard_map``; XLA compiles
the pmean into ICI collectives and overlaps them with backward compute (the
compile-time analogue of Horovod's tensor-fusion buffer, SURVEY.md H2).

Anchors enter as a compile-time constant (ops/anchors.py), and target
assignment (IoU + argmax matching) runs on device under ``stop_gradient``,
per the north star (BASELINE.json:5).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu import losses as losses_lib
from batchai_retinanet_horovod_coco_tpu.data import pipeline as pipeline_lib
from batchai_retinanet_horovod_coco_tpu.obs import numerics as numerics_lib
from batchai_retinanet_horovod_coco_tpu.obs.numerics import NumericsConfig
from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
from batchai_retinanet_horovod_coco_tpu.ops import matching as matching_lib
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.train.state import TrainState, model_variables


def _forward_and_loss(
    model,
    state: TrainState,
    params,
    images: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_labels: jnp.ndarray,
    gt_mask: jnp.ndarray,
    anchors: jnp.ndarray,
    loss_config: losses_lib.LossConfig,
    matching_config: matching_lib.MatchingConfig,
    train: bool,
):
    variables = dict(model_variables(state), params=params)
    has_bn = "batch_stats" in variables
    # uint8 batches normalize here, on device (data/pipeline.normalize_images).
    images = pipeline_lib.normalize_images(images)

    # NHWC-direct loss path: raw per-level head outputs, no anchor-major
    # retile/concat (losses.total_loss_compact_nhwc — measured ~4 ms/step
    # of layout traffic at the flagship bucket).  The Pallas focal kernel
    # consumes the concatenated (B, A, K) form instead.
    return_levels = False if loss_config.pallas_focal else "nhwc"
    apply_kwargs = dict(train=train, return_levels=return_levels)
    if has_bn and train:
        outputs, mutated = model.apply(
            variables, images, mutable=["batch_stats"], **apply_kwargs
        )
        new_batch_stats = mutated["batch_stats"]
    else:
        outputs = model.apply(variables, images, **apply_kwargs)
        new_batch_stats = state.batch_stats

    # On-device target assignment; no gradients flow into the matching.
    # Compact form: integer labels instead of a dense (A, K) one-hot — the
    # focal loss fuses the implicit one-hot (losses.focal_loss_compact).
    # Batched entrypoint: fused Pallas assignment on TPU, vmapped XLA
    # elsewhere (ops/matching.py).
    # Planar (B, 4, A) box targets on the NHWC path: dense lane layout end
    # to end instead of the 32x-padded 4-minor form (ops.matching docstring).
    planar = return_levels == "nhwc"
    targets = matching_lib.anchor_targets_compact_batched(
        anchors, gt_boxes, gt_labels, gt_mask, matching_config,
        planar_box_targets=planar,
    )
    targets = jax.tree.map(lax.stop_gradient, targets)

    if return_levels == "nhwc":
        metrics = losses_lib.total_loss_compact_nhwc(
            outputs["cls_levels"],
            outputs["box_levels"],
            targets.matched_labels,
            targets.box_targets,
            targets.state,
            model.config.anchors_per_location,
            loss_config,
            planar_box_targets=True,
        )
    else:
        metrics = losses_lib.total_loss_compact(
            outputs["cls_logits"],
            outputs["box_deltas"],
            targets.matched_labels,
            targets.box_targets,
            targets.state,
            loss_config,
        )
    metrics["num_pos"] = jnp.sum(
        (targets.state == matching_lib.POSITIVE).astype(jnp.float32)
    )
    return metrics["loss"], (metrics, new_batch_stats)


def resolve_kernel_schedule(
    loss_config: losses_lib.LossConfig,
    matching_config: matching_lib.MatchingConfig,
    device_kind: str | None = None,
) -> tuple[losses_lib.LossConfig, matching_lib.MatchingConfig]:
    """Fill schedule-resolved kernel params (the train-side consumer of
    the tune/ registry): focal impl + fwd/bwd tiles, matching impl + tile.

    ``None`` fields mean "look the measured winner up in the per-device
    schedule" (tune/schedule.py; built-in defaults reproduce the
    hand-picked values, so an untuned device behaves exactly as before
    ISSUE 6).  Explicit values always win — a CLI/test override must not
    be silently re-tuned.  ``matching.impl == "auto"`` preserves the
    backend-conditional dispatch (fused on TPU, jnp elsewhere).
    """
    import dataclasses as _dc

    from batchai_retinanet_horovod_coco_tpu.tune import (
        schedule as schedule_lib,
    )

    sched = schedule_lib.lookup(device_kind)
    m, f = sched["matching"], sched["focal"]
    if matching_config.pallas_tile_a is None:
        matching_config = _dc.replace(
            matching_config, pallas_tile_a=int(m["tile_a"])
        )
    if matching_config.fused_pallas is None and m["impl"] != "auto":
        matching_config = _dc.replace(
            matching_config, fused_pallas=m["impl"] == "pallas"
        )
    if loss_config.pallas_focal is None and f["impl"] != "auto":
        loss_config = _dc.replace(
            loss_config, pallas_focal=f["impl"] == "pallas"
        )
    if loss_config.focal_fwd_tile_a is None:
        loss_config = _dc.replace(
            loss_config, focal_fwd_tile_a=int(f["fwd_tile_a"])
        )
    if loss_config.focal_bwd_tile_a is None:
        loss_config = _dc.replace(
            loss_config, focal_bwd_tile_a=int(f["bwd_tile_a"])
        )
    return loss_config, matching_config


def _make_local_step(model, anchors, loss_config, matching_config):
    """The per-shard (or single-device) grad computation every step shares."""

    def local_step(state: TrainState, batch: dict[str, Any]):
        (_, (metrics, new_bs)), grads = jax.value_and_grad(
            lambda p: _forward_and_loss(
                model, state, p,
                batch["images"], batch["gt_boxes"], batch["gt_labels"],
                batch["gt_mask"], anchors, loss_config,
                matching_config, train=True,
            ),
            has_aux=True,
        )(state.params)
        return grads, metrics, new_bs

    return local_step


def _cached_step_entry(make_step: Callable) -> Callable:
    """Lazy per-structure compile cache + AOT surface, shared by the
    ZeRO and comm step flavors.

    The shard_map spec trees depend on the state's tree structure, which
    only the caller's state knows — so the step is built lazily per
    structure and cached, keyed on the treedefs (a structurally
    different state, e.g. a swapped optimizer or a comm-policy change,
    gets fresh partition specs instead of stale ones).  The ``.lower``
    attribute is the AOT point the loop's multi-process compile barrier
    uses (train/loop.py::_compile_barrier)."""
    cache: dict[Any, Callable] = {}

    def get_step(state: TrainState) -> Callable:
        key = (
            jax.tree.structure(state.opt_state),
            jax.tree.structure(state.params),
            jax.tree.structure(state.batch_stats),
            jax.tree.structure(state.comm_state),
        )
        if key not in cache:
            cache[key] = make_step(state)
        return cache[key]

    def entry(state: TrainState, batch: dict[str, Any]):
        return get_step(state)(state, batch)

    entry.lower = lambda state, batch: get_step(state).lower(state, batch)
    return entry


def _global_math_step(local_step, numerics: NumericsConfig | None = None):
    """Plain global-batch step body: grads → metrics → update.

    Serves both the single-device step (jit) and the spatially partitioned
    step (jit + sharding constraints, where GSPMD turns the global
    reductions into collectives) — ONE definition so metrics/update changes
    cannot drift between them.
    """
    numerics = numerics or NumericsConfig()

    def train_step(state: TrainState, batch: dict[str, Any]):
        grads, metrics, new_bs = local_step(state, batch)
        # SURVEY.md §5.5: grad-norm is a first-class per-step metric —
        # computed ONCE here and fed to the clip chain via extra args
        # (clip_by_global_norm_precomputed), so the recorded value IS the
        # pre-clip norm the clip acted on, never a recomputation.
        gnorm = optax.global_norm(grads)
        metrics["grad_norm"] = gnorm
        new_state = state.apply_gradients(
            grads, new_bs, loss_value=metrics["loss"], grad_norm=gnorm
        )
        # Norm of the POST-update params: the loss above was computed
        # from the pre-update params, so it cannot witness a poisoned
        # update — this can, and the loop checks it before any
        # checkpoint save (a norm read of params the next step reloads
        # anyway; cost is noise).
        metrics["param_norm"] = optax.global_norm(new_state.params)
        if numerics.enabled:
            # In-step numerics summary (ISSUE 10): ~2 extra reduces; the
            # disabled step's HLO is unchanged (trace-time Python gate).
            metrics.update(
                numerics_lib.step_summary(
                    grads, state.params, new_state.params,
                    metrics["param_norm"], numerics,
                )
            )
        return new_state, metrics

    return train_step


def make_train_step(
    model,
    image_hw: tuple[int, int],
    num_classes: int,
    mesh: Mesh | None = None,
    loss_config: losses_lib.LossConfig = losses_lib.LossConfig(),
    matching_config: matching_lib.MatchingConfig = matching_lib.MatchingConfig(),
    anchor_config: anchors_lib.AnchorConfig | None = None,
    donate_state: bool = True,
    shard_weight_update: bool = False,
    quantized_allreduce: bool = False,
    comm=None,
    topology=None,
    numerics: NumericsConfig | None = None,
) -> Callable[[TrainState, dict[str, Any]], tuple[TrainState, dict[str, jnp.ndarray]]]:
    """Build the jitted train step for one shape bucket.

    With ``mesh``: the step is a ``shard_map`` over the mesh — the batch is
    consumed shard-by-shard (each device sees batch/n_devices images),
    gradients and metrics are ``lax.pmean``-ed over the ``data`` axis, and
    every device applies the identical update to its replicated state.

    Without ``mesh``: plain single-device jit (BASELINE.json configs[1]).

    ``shard_weight_update`` (requires ``mesh``): ZeRO-style mode — gradients
    reduce-scatter instead of all-reduce, each device updates its 1/N of the
    params with its 1/N optimizer-state shard, updated params all-gather
    back (parallel/zero.py).  ``state.opt_state`` must come from
    ``init_sharded_opt_state`` and ``state.tx`` from
    ``make_optimizer(..., shard_clip_axis=DATA_AXIS)`` so gradient clipping
    uses the global (cross-shard) norm.

    ``comm`` (a ``comm.CommConfig``; requires ``mesh``): the gradient-
    communication policy (ISSUE 13).  On the plain-DP path the all-reduce
    becomes the bucketed, error-feedback int8/bf16 scheme of
    ``comm/compress.py`` (exact f32 reduce-scatter, EF add-back from
    ``state.comm_state``, per-block compressed gather; with
    ``comm.overlap`` each schedule stage's collective is issued inside
    the backward via ``comm/overlap.py``).  Combined with
    ``shard_weight_update`` the gradient reduce-scatter stays exact and
    the compression moves to the ZeRO param gather (quantized UPDATE
    gather with per-leaf EF — the old exclusivity is lifted).  The
    pre-clip ``grad_norm`` is computed on the DEQUANTIZED gradients, so
    the clip chain acts on the values the optimizer actually consumes.
    EF health lands in the metrics (``ef_residual_norm`` /
    ``ef_saturation`` / ``comm_compressed_bytes``).  With ``comm`` unset
    (or ``compress="none"``) the compiled step is byte-identical to the
    pre-ISSUE-13 program.

    ``topology`` (a ``parallel.mesh.CommTopology``; ISSUE 16): the
    two-level slice x intra-slice device grouping.  When it names more
    than one slice AND ``comm``'s per-hop modes differ, the gradient
    collective becomes the HIERARCHICAL tree — exact f32
    reduce-scatter within each ICI slice, quantized exchange only on
    the cross-slice DCN hop, exact intra-slice all-gather — with the
    EF residuals keyed per hop and the wire accounting split into
    ``comm_ici_bytes`` / ``comm_dcn_bytes``.  Otherwise the hierarchy
    degenerates and the step compiles the FLAT tree at the effective
    single-hop mode, byte-identical to passing no topology at all
    (single-slice worlds run the whole tree at ``ici_mode``, i.e.
    exact by default — there is no slow wire to compress).  The mesh
    must be built with the same topology (``make_mesh(..., topology)``)
    so slice-index devices sit in the interleaved order the groups
    assume.  ZeRO runs ignore the topology (the update gather stays
    flat) with a structured warning.

    ``quantized_allreduce``: DEPRECATED alias for
    ``comm=CommConfig(compress="int8")`` (stateless unless the state
    carries EF residuals) — the pre-ISSUE-13 per-leaf path is gone.

    ``numerics`` (obs/numerics.py): enable the fused in-step numerics
    summary — update/param ratio, non-finite gradient count, per-layer-
    group norms, and (mesh steps) the cross-replica agreement probe.
    Disabled (the default) the compiled program is unchanged.

    The returned callable takes (state, batch_dict) where batch_dict holds
    ``images, gt_boxes, gt_labels, gt_mask`` (leading axis = GLOBAL batch)
    and returns (new_state, metrics).
    """
    numerics = numerics or NumericsConfig()
    if shard_weight_update and mesh is None:
        raise ValueError("shard_weight_update requires a mesh")
    if quantized_allreduce and mesh is None:
        raise ValueError("quantized_allreduce requires a mesh")
    if quantized_allreduce and comm is None:
        # Deprecated alias (ISSUE 13): the bool maps onto the comm
        # subsystem's int8 policy.  EF engages iff the caller's state
        # carries comm residuals (comm.init_comm_state).
        from batchai_retinanet_horovod_coco_tpu.comm import CommConfig

        comm = CommConfig(compress="int8")
    # Hop-policy resolution (ISSUE 16): the hierarchical tree engages
    # only for a real multi-slice topology with distinct per-hop modes;
    # every other case resolves to the flat tree BEFORE tracing so the
    # degenerate paths compile byte-identical HLO.
    comm_topology = None
    if comm is not None and topology is not None:
        if shard_weight_update:
            if comm.hierarchical_with(topology):
                import warnings

                warnings.warn(
                    "comm topology has no effect with "
                    "shard_weight_update: the ZeRO path compresses the "
                    "post-update gather, which stays flat — the "
                    "hierarchical tree is a DP-path mechanism"
                )
        elif comm.hierarchical_with(topology):
            comm_topology = topology
        else:
            comm = comm.flat_equivalent(topology)
    comm_on = comm is not None and comm.enabled
    if comm_on and mesh is None:
        raise ValueError("comm compression requires a mesh")
    if comm_topology is not None and mesh is not None:
        if comm_topology.num_devices != mesh.size:
            raise ValueError(
                f"topology is {comm_topology.num_slices}x"
                f"{comm_topology.slice_size} = "
                f"{comm_topology.num_devices} devices but the mesh has "
                f"{mesh.size}"
            )
    if comm_on and comm.overlap and shard_weight_update:
        # The ZeRO flavor's compressed collective is the POST-update
        # gather — there is no backward-stage collective for overlap to
        # move.  Warn loudly rather than let the flag silently no-op.
        import warnings

        warnings.warn(
            "comm.overlap has no effect with shard_weight_update: the "
            "ZeRO path compresses the post-update gather, not the "
            "backward-pass gradient collectives (comm/overlap.py is a "
            "DP-path mechanism)"
        )
    anchors = jnp.asarray(
        anchors_lib.anchors_for_image_shape(image_hw, anchor_config or anchors_lib.AnchorConfig())
    )

    # Schedule-resolved kernel params (tune/): tile shapes + impl choices
    # come from the per-device registry unless explicitly pinned.
    loss_config, matching_config = resolve_kernel_schedule(
        loss_config, matching_config
    )
    local_step = _make_local_step(model, anchors, loss_config, matching_config)

    if mesh is None:
        return jax.jit(
            _global_math_step(local_step, numerics),
            donate_argnums=(0,) if donate_state else (),
        )

    batch_spec = {k: P(DATA_AXIS) for k in ("images", "gt_boxes", "gt_labels", "gt_mask")}

    if shard_weight_update:
        from batchai_retinanet_horovod_coco_tpu.parallel import zero

        if comm_on:
            from batchai_retinanet_horovod_coco_tpu.comm import (
                compress as compress_lib,
            )

        def reduce_metrics(metrics):
            num_pos = lax.psum(metrics["num_pos"], DATA_AXIS)
            metrics = lax.pmean(metrics, DATA_AXIS)
            metrics["num_pos"] = num_pos
            return metrics

        def state_specs(state: TrainState) -> TrainState:
            """Per-leaf spec tree: everything replicated except opt_state
            (and the comm EF residuals, which shard the same way)."""
            return TrainState(
                step=P(),
                params=jax.tree.map(lambda _: P(), state.params),
                batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
                opt_state=zero.opt_state_partition_specs(state.opt_state),
                tx=state.tx,
                comm_state=jax.tree.map(
                    lambda _: P(DATA_AXIS), state.comm_state
                ),
            )

        def make_zero_step(state_template: TrainState):
            specs = state_specs(state_template)
            zplan = (
                compress_lib.plan_buckets(state_template.params, comm)
                if comm_on
                else None
            )

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=(specs, P()),
                check_vma=False,
            )
            def zero_step(state: TrainState, batch: dict[str, Any]):
                grads, metrics, new_bs = local_step(state, batch)
                if numerics.enabled and numerics.replica_agreement:
                    # Cross-replica probe on the LOCAL pre-reduce grads:
                    # a desynced replica's local norm diverges from its
                    # peers' long before the (averaged) loss shows it.
                    metrics["replica_agreement"] = (
                        numerics_lib.replica_agreement(
                            optax.global_norm(grads), DATA_AXIS
                        )
                    )
                metrics = reduce_metrics(metrics)
                if state.batch_stats:
                    new_bs = lax.pmean(new_bs, DATA_AXIS)
                # Reduce-scatter + sharded update + all_gather replaces the
                # pmean-allreduce + replicated update (parallel/zero.py).
                # Comm-on (ISSUE 13): the gradient reduce-scatter stays
                # exact (it feeds the sharded optimizer and the global
                # clip norm); the f32 param gather is replaced by the
                # bucketed compressed UPDATE gather with per-leaf EF
                # residuals (comm/compress.zero_gather_updates).
                comm_out: dict[str, Any] = {}
                gather = None
                if comm_on:
                    comm_cs = (
                        state.comm_state
                        if isinstance(state.comm_state, dict)
                        else {}
                    )

                    def gather(updates, params):
                        new_p, new_res, sat = (
                            compress_lib.zero_gather_updates(
                                updates, params, comm_cs, zplan, comm,
                                DATA_AXIS, mesh.size,
                            )
                        )
                        comm_out["res"] = new_res
                        comm_out["sat"] = sat
                        return new_p

                new_params, new_opt, info = zero.sharded_update(
                    state.tx,
                    grads,
                    state.opt_state,
                    state.params,
                    n=mesh.size,
                    loss_value=metrics["loss"],
                    gather_updates=gather,
                )
                metrics.update(info)
                # Post-update param norm (see the single-device step): the
                # gathered new_params are replicated, so the norm is too.
                metrics["param_norm"] = optax.global_norm(new_params)
                if numerics.enabled:
                    # Hand-assembled summary: the reduced gradient only
                    # ever exists as 1/N shards here, so the non-finite
                    # count psums the LOCAL counts (a NaN anywhere
                    # poisons the reduce-scatter, so local detection is
                    # global detection) and group norms are the pmean of
                    # per-replica local-grad norms; params are
                    # replicated, so the update ratio is the same math
                    # as the replicated step's.
                    metrics["nonfinite_grads"] = lax.psum(
                        numerics_lib.nonfinite_count(grads), DATA_AXIS
                    )
                    metrics["update_ratio"] = numerics_lib.update_ratio(
                        state.params, new_params, metrics["param_norm"]
                    )
                    if numerics.per_group:
                        for key, norm in numerics_lib.group_norms(
                            grads
                        ).items():
                            metrics[f"gnorm/{key}"] = lax.pmean(
                                norm, DATA_AXIS
                            )
                new_comm_state = state.comm_state
                if comm_on:
                    metrics.update(
                        compress_lib.comm_metrics(
                            zplan, comm_out["res"], comm_out["sat"],
                            DATA_AXIS, mesh.size, zero=True,
                        )
                    )
                    if isinstance(state.comm_state, dict):
                        new_comm_state = comm_out["res"]
                new_state = state.replace(
                    step=state.step + 1,
                    params=new_params,
                    batch_stats=new_bs,
                    opt_state=new_opt,
                    comm_state=new_comm_state,
                )
                return new_state, metrics

            return jax.jit(
                zero_step, donate_argnums=(0,) if donate_state else ()
            )

        return _cached_step_entry(make_zero_step)

    if comm_on:
        # Comm subsystem path (ISSUE 13): bucketed compressed all-reduce
        # with error feedback, optionally staged inside the backward pass
        # (comm/overlap.py).  A separate shard_map flavor — the exact
        # path below stays byte-identical to pre-ISSUE-13.
        from batchai_retinanet_horovod_coco_tpu.comm import (
            compress as compress_lib,
        )
        from batchai_retinanet_horovod_coco_tpu.comm import (
            overlap as overlap_lib,
        )

        def make_comm_step(state_template: TrainState):
            plan = compress_lib.plan_buckets(
                state_template.params, comm, comm_topology
            )
            spec = TrainState(
                step=P(),
                params=jax.tree.map(lambda _: P(), state_template.params),
                batch_stats=jax.tree.map(
                    lambda _: P(), state_template.batch_stats
                ),
                opt_state=jax.tree.map(
                    lambda _: P(), state_template.opt_state
                ),
                tx=state_template.tx,
                comm_state=jax.tree.map(
                    lambda _: P(DATA_AXIS), state_template.comm_state
                ),
            )
            grad_fn = (
                overlap_lib.make_overlap_grad_fn(
                    plan, comm, DATA_AXIS, mesh.size, comm_topology
                )
                if comm.overlap
                else None
            )

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(spec, batch_spec),
                out_specs=(spec, P()),
                check_vma=False,
            )
            def comm_step(state: TrainState, batch: dict[str, Any]):
                comm_cs = (
                    state.comm_state
                    if isinstance(state.comm_state, dict)
                    else {}
                )
                if comm.overlap:
                    # Each stage's compressed collective fires inside
                    # the backward via the custom-vjp taps; the grads
                    # come out ALREADY reduced and the EF residuals /
                    # saturation ride the cotangent channel.  (The
                    # replica-agreement probe needs local pre-reduce
                    # grads, which this schedule never materializes as
                    # one tree — structurally absent here.)
                    def loss_of_params(p):
                        return _forward_and_loss(
                            model, state, p,
                            batch["images"], batch["gt_boxes"],
                            batch["gt_labels"], batch["gt_mask"],
                            anchors, loss_config, matching_config,
                            train=True,
                        )

                    (_, (metrics, new_bs)), grads, new_comm, sat = (
                        grad_fn(loss_of_params, state.params, comm_cs)
                    )
                else:
                    grads, metrics, new_bs = local_step(state, batch)
                    if numerics.enabled and numerics.replica_agreement:
                        metrics["replica_agreement"] = (
                            numerics_lib.replica_agreement(
                                optax.global_norm(grads), DATA_AXIS
                            )
                        )
                    # One fused pass: exact f32 reduce-scatter + EF
                    # add-back + compressed gather per bucket.
                    grads, new_comm, sat = compress_lib.reduce_tree(
                        grads, comm_cs, plan, comm, DATA_AXIS, mesh.size,
                        comm_topology,
                    )
                num_pos = lax.psum(metrics["num_pos"], DATA_AXIS)
                metrics = lax.pmean(metrics, DATA_AXIS)
                metrics["num_pos"] = num_pos
                # Pre-clip global norm of the DEQUANTIZED gradients —
                # the values the optimizer actually consumes — shared
                # with the clip chain (clip_by_global_norm_precomputed).
                gnorm = optax.global_norm(grads)
                metrics["grad_norm"] = gnorm
                if state.batch_stats:
                    new_bs = lax.pmean(new_bs, DATA_AXIS)
                new_state = state.apply_gradients(
                    grads, new_bs, loss_value=metrics["loss"],
                    grad_norm=gnorm,
                )
                metrics["param_norm"] = optax.global_norm(new_state.params)
                metrics.update(
                    compress_lib.comm_metrics(
                        plan, new_comm, sat, DATA_AXIS, mesh.size,
                        topology=comm_topology,
                    )
                )
                if isinstance(state.comm_state, dict):
                    new_state = new_state.replace(comm_state=new_comm)
                if numerics.enabled:
                    metrics.update(
                        numerics_lib.step_summary(
                            grads, state.params, new_state.params,
                            metrics["param_norm"], numerics,
                        )
                    )
                return new_state, metrics

            return jax.jit(
                comm_step, donate_argnums=(0,) if donate_state else ()
            )

        # Lazy per-structure cache + AOT surface, shared with the ZeRO
        # flavor: the comm-state tree structure is the caller's (empty
        # for the stateless deprecated alias, per-bucket dict with EF).
        return _cached_step_entry(make_comm_step)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def sharded_step(state: TrainState, batch: dict[str, Any]):
        grads, metrics, new_bs = local_step(state, batch)
        if numerics.enabled and numerics.replica_agreement:
            # Cross-replica probe BEFORE the allreduce: per-replica local
            # norms vs the axis min/max — the silent-desync detector the
            # averaged gradients cannot provide (obs/numerics.py).
            metrics["replica_agreement"] = numerics_lib.replica_agreement(
                optax.global_norm(grads), DATA_AXIS
            )
        # THE allreduce: Horovod's NCCL ring → one compiled pmean over ICI.
        grads = lax.pmean(grads, DATA_AXIS)
        num_pos = lax.psum(metrics["num_pos"], DATA_AXIS)  # a count, not a mean
        metrics = lax.pmean(metrics, DATA_AXIS)
        metrics["num_pos"] = num_pos
        # Pre-clip global norm, computed once and shared with the clip
        # chain via extra args (clip_by_global_norm_precomputed).
        gnorm = optax.global_norm(grads)
        metrics["grad_norm"] = gnorm
        if state.batch_stats:
            new_bs = lax.pmean(new_bs, DATA_AXIS)  # sync-BN semantics
        new_state = state.apply_gradients(
            grads, new_bs, loss_value=metrics["loss"], grad_norm=gnorm
        )
        # Post-update param norm (see the single-device step for why).
        metrics["param_norm"] = optax.global_norm(new_state.params)
        if numerics.enabled:
            # Post-allreduce grads + params are replicated, so the shared
            # summary is replicated-out safe here.
            metrics.update(
                numerics_lib.step_summary(
                    grads, state.params, new_state.params,
                    metrics["param_norm"], numerics,
                )
            )
        return new_state, metrics

    return jax.jit(sharded_step, donate_argnums=(0,) if donate_state else ())


def _degenerate_strided_conv_heights(
    image_h: int, num_space: int
) -> list[int]:
    """Stride-2 3x3 conv input heights inside the XLA weight-grad bug zone.

    The model family's stride-2 3x3 convs consume maps at H/4, H/8, H/16
    (ResNet stage3/4/5 ``conv2``), H/32 (FPN P6 reads C5) and H/64 (P7
    reads P6).  Empirical risk zone (see make_train_step_spatial): shards
    >= 8 AND rows-per-shard in [0.5, 2) — 2 rows/shard and the
    replication-handled H < num_space/2 maps measured exact, as did every
    layout at <= 4 shards (including exactly 1 row/shard, which IS broken
    at 8 shards; the boundary is shard-count-dependent, so the canary test
    pins both sides of it).

    Round-5 16-shard sweep (strided_conv_weight_grad.py --probe, pinned
    by test_xla_strided_conv_grad_canary_16shard): broken layouts at 16
    shards are rows/shard in {0.5, 1} (44%/41%), with 1.5, 2 and the
    replicated 0.25 rows exact — every broken layout falls inside this
    zone, so the [n/2, 2n) rule is MEASURED (as a conservative superset;
    1.5 rows over-refuses) at 4, 8 and 16 shards rather than
    extrapolated.
    """
    if num_space < 8:
        return []
    # Ceil division: the stride-2 downsample chain produces ceil(H/d)
    # extents (SAME padding), and at the zone's lower edge floor is one
    # row short — e.g. H=224, 8 shards: floor gives 3 (outside [4, 16))
    # but the real P7 input is ceil(224/64)=4, the measured-wrong
    # 0.5-rows-per-shard layout.
    heights = [-(-image_h // d) for d in (4, 8, 16, 32, 64)]
    return [h for h in heights if num_space / 2 <= h < 2 * num_space]


# Backbones whose spatial-step gradients measured EXACT on the virtual
# mesh rig (round-5 f64 probes).  Deep backbones are NOT on the list: see
# make_train_step_spatial's "Data-axis envelope" docstring section.
_SPATIAL_GRAD_VALIDATED_BACKBONES = frozenset({"resnet_test"})


def _data_axis_risky_stage_heights(image_h: int, num_space: int) -> list[int]:
    """Backbone-stage map heights inside the round-5 residual-chain bug
    zone: stages run at ceil(H/4..H/32), and the measured model-level
    divergence (see make_train_step_spatial's "Data-axis envelope")
    requires some residual-stage map at <= 1 row per shard — hw-64
    models (min stage rows 0.5-1) diverge at data >= 2 while hw-256
    models (min 4 rows) measure clean, matching the minimal repro's
    boundary (1 row broken at space=2; 1.5+ rows exact)."""
    if num_space < 2:
        return []
    heights = [-(-image_h // d) for d in (4, 8, 16, 32)]
    return [h for h in heights if h <= num_space]


def make_train_step_spatial(
    model,
    image_hw: tuple[int, int],
    num_classes: int,
    mesh: Mesh,
    loss_config: losses_lib.LossConfig = losses_lib.LossConfig(),
    matching_config: matching_lib.MatchingConfig = matching_lib.MatchingConfig(),
    anchor_config: anchors_lib.AnchorConfig | None = None,
    donate_state: bool = True,
    allow_degenerate_spatial_sharding: bool = False,
    allow_unvalidated_bf16: bool = False,
    allow_data_axis_divergence: bool = False,
    numerics: NumericsConfig | None = None,
) -> Callable[[TrainState, dict[str, Any]], tuple[TrainState, dict[str, jnp.ndarray]]]:
    """Train step with the IMAGE sharded across chips (spatial partitioning).

    The training-side analogue of sequence/context parallelism
    (SURVEY.md §5.7, same idea as ``evaluate.detect.make_detect_fn_spatial``):
    the batch shards over ``data`` AND each image's H axis shards over
    ``spatial_axis``, so a 2-D mesh trains images too large (or batches too
    small) for pure DP.  Built with ``jit`` + sharding constraints, not
    ``shard_map``: spatially partitioned convs need GSPMD's halo-exchange
    machinery — ring-attention's "pass the boundary" pattern, compiled
    automatically — which per-device code would have to hand-roll.

    The step body is the plain single-device global-batch math (no
    explicit pmean): under GSPMD the compiler partitions the forward,
    inserts the halos, and turns the global loss/gradient reductions into
    the right collectives.  Within the supported sharding envelope (below)
    gradients match the single-device step to 1e-5-class agreement
    (pinned by tests/distributed/test_spatial_train.py).

    Sharding envelope: XLA's SPMD partitioner mis-computes the WEIGHT
    gradient of a stride-2 3x3 conv whose per-shard input extent collapses
    to ~one row (isolated repro in
    tests/distributed/test_spatial_train.py::test_xla_strided_conv_grad_canary:
    ~45% relative error on that conv's weight grad, persisting in f64 —
    a genuinely different sum, not rounding — with both the GSPMD and
    Shardy partitioners, jax 0.9.0; forward and grad-input are exact).
    The boundary is EMPIRICAL and shard-count-dependent (round-4 probes,
    pinned by the canary test): at 8 shards, 1 row/shard is badly wrong
    (44%) and half-a-row/shard measurably wrong (1e-4-class on params),
    while 2 rows/shard and the tiny H < num_space/2 maps (which the
    partitioner handles via replication) are exact to 1e-15; at <= 4
    shards every layout measured exact, INCLUDING 1 row/shard.  The
    model family's stride-2 3x3 convs consume maps of H/4, H/8, H/16
    (backbone stage3/4/5), H/32 (FPN P6 from C5) and H/64 (P7 from P6),
    so this factory REFUSES meshes with ``space >= 8`` where any of those
    heights lands in the measured risk zone
    [num_space/2, 2*num_space).  ``allow_degenerate_spatial_sharding=True``
    overrides (the parity tests use it to pin the divergence magnitude);
    expect 1e-3-class relative gradient error in the affected conv
    kernels until the upstream fix (at which point the canary test fails
    and this guard should be dropped).

    Dtype envelope: bf16 models at flagship width are MISCOMPILED by the
    SPMD partitioner under this step's shardings (round-4 finding, pinned
    by test_spatial_train.py::test_xla_bf16_spatial_step_canary): with the
    box gradient in the graph, the forward cls_loss VALUE comes out wrong
    — 1.128 → 1.420 (gn) / 2.82 (frozen_bn) with gradients 14–60x off —
    deterministically, at 256-wide heads, while f32 at the same width and
    bf16 at width 64 are exact; the wrong value changes when unrelated
    graph consumers (e.g. ``optax.global_norm(grads)``) are added, the
    signature of a partitioner miscompilation, and persists across the
    mask/custom-VJP/planar-layout variants of the loss.  Reproduced on the
    virtual CPU mesh (jax 0.9.0); real multi-chip TPU is unavailable to
    this rig, so TPU is UNVALIDATED rather than known-good.  The factory
    therefore refuses non-f32 models; ``allow_unvalidated_bf16=True``
    overrides for users who have validated their own backend (run one
    step of this factory's output against ``make_train_step(mesh=None)``
    on an identical batch first — the canary shows exactly how).

    Data-axis envelope (round-5 finding): on DEEP backbones whose
    small stages land at <= 1 row per shard, combining a data axis >= 2
    with space sharding makes the compiled backward diverge from the
    single-device gradients — measured per-step param error (f64,
    reduced-width resnet50, hw 64, so NOT rounding): L2 4.1e-6 at
    data=1, 2.8e-4 at data=2, 6.5e-4 at 4, 2.1e-3 at 8, 7.2e-3 at 16
    (~x3 per data doubling; identical at space=2 and space=4).  The
    minimal trigger is >= 2 chained residual blocks of 3x3 convs on an
    H=2 map at (data>=2, space=2) — FD-proven wrong backward, up to
    4.1e5x relative error
    (scripts/xla_repros/spatial_residual_chain_grad.py; canary:
    test_spatial_train.py::test_xla_spatial_data_axis_grad_canary).
    Clean by measurement: the shallow CI backbone everywhere,
    ``(data, 1)`` meshes (bit-exact), pure-spatial ``(1, space)``
    meshes (4e-6-class), and — key for real workloads — the SAME deep
    model at hw 256, where every stage runs >= 4 rows/shard (param L2
    5.6e-8 at (4, 2)); flagship 800-class buckets keep every stage
    >= 3 rows/shard at space <= 4 and are therefore outside the zone.
    The factory refuses data >= 2 only when some backbone-stage height
    lands at <= 1 row per shard (``_data_axis_risky_stage_heights``)
    on a non-shallow backbone; ``allow_data_axis_divergence=True``
    overrides (the dryrun uses it to pin the divergence magnitude).

    Pallas kernels are opaque to GSPMD and cannot be spatially
    partitioned: the fused assignment is forced off (the vmapped XLA
    matching path partitions fine) and a ``pallas_focal`` loss config is
    rejected rather than silently replicated.
    """
    import dataclasses as _dc

    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import SPACE_AXIS

    model_dtype = jnp.dtype(model.config.dtype)
    if model_dtype != jnp.dtype(jnp.float32) and not allow_unvalidated_bf16:
        raise ValueError(
            f"spatial partitioning with a {model_dtype.name} model is "
            "refused: the SPMD partitioner miscompiles the bf16 train "
            "step at flagship width (wrong cls_loss values, 14-60x wrong "
            "gradients — see make_train_step_spatial's docstring and the "
            "bf16 spatial canary test).  Train spatially in f32 "
            "(--f32 with --spatial-shards), or pass "
            "allow_unvalidated_bf16=True after validating one step "
            "against the single-device step on your backend"
        )

    num_space = dict(mesh.shape).get(SPACE_AXIS, 1)
    if not allow_degenerate_spatial_sharding:
        risky = _degenerate_strided_conv_heights(image_hw[0], num_space)
        if risky:
            raise ValueError(
                f"space axis size {num_space} is too large for image "
                f"height {image_hw[0]}: stride-2 3x3 conv input maps of "
                f"height {risky} would land in the measured envelope "
                "where XLA's SPMD partitioner mis-computes strided-conv "
                "weight gradients (~[0.5, 2) rows per shard at >= 8 "
                "shards; see make_train_step_spatial docstring).  Use a "
                "smaller --spatial-shards for this bucket (space <= 4 is "
                "always outside the envelope), or pass "
                "allow_degenerate_spatial_sharding=True to accept "
                "1e-3-class gradient error in the affected conv kernels"
            )
    num_data = dict(mesh.shape).get(DATA_AXIS, 1)
    risky_stage = _data_axis_risky_stage_heights(image_hw[0], num_space)
    if (
        num_space > 1
        and num_data > 1
        and risky_stage
        and model.config.backbone not in _SPATIAL_GRAD_VALIDATED_BACKBONES
        and not allow_data_axis_divergence
    ):
        raise ValueError(
            f"spatial mesh (data={num_data}, space={num_space}) with "
            f"backbone {model.config.backbone!r} at image height "
            f"{image_hw[0]} is refused: backbone-stage maps of height "
            f"{risky_stage} land at <= 1 row per shard, where the "
            "partitioned backward of deep (residual-chain) backbones "
            "diverges from the single-device gradients once the data "
            "axis exceeds 1 (measured f64: 2.8e-4 per-step param L2 at "
            "data=2 growing ~3x per doubling — see "
            "make_train_step_spatial's 'Data-axis envelope').  Use a "
            "pure-spatial (1, space) mesh (device count equal to the "
            "spatial shard count), larger images (flagship 800-class "
            "buckets keep every stage >= 3 rows/shard at space <= 4 and "
            "measure clean), a plain DP mesh, or pass "
            "allow_data_axis_divergence=True to accept the measured "
            "gradient error"
        )
    if loss_config.pallas_focal:
        raise ValueError(
            "pallas_focal is incompatible with spatial partitioning: a "
            "pallas_call is opaque to GSPMD, so the head outputs would be "
            "replicated instead of sharded — use the default XLA focal path"
        )
    # Resolve the schedule first (tile fields), then FORCE the GSPMD-opaque
    # kernels off: a per-device schedule winner must not re-enable what
    # spatial partitioning cannot shard (only an EXPLICIT pallas_focal=True
    # reaches the raise above).
    loss_config, matching_config = resolve_kernel_schedule(
        loss_config, matching_config
    )
    loss_config = _dc.replace(loss_config, pallas_focal=False)
    matching_config = _dc.replace(matching_config, fused_pallas=False)
    anchors = jnp.asarray(
        anchors_lib.anchors_for_image_shape(
            image_hw, anchor_config or anchors_lib.AnchorConfig()
        )
    )
    # Numerics summary rides the global-math body (grads are global under
    # GSPMD); the per-replica agreement probe needs a named axis shard_map
    # does not exist here, so it is structurally absent on this path.
    train_step = _global_math_step(
        _make_local_step(model, anchors, loss_config, matching_config),
        numerics,
    )

    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
        spatial_batch_shardings,
    )

    rep = NamedSharding(mesh, P())
    # ONE definition of the batch layout, shared with the loop's
    # _device_batch placement (parallel/mesh.py).
    batch_shardings = spatial_batch_shardings(mesh)
    return jax.jit(
        train_step,
        in_shardings=(rep, batch_shardings),
        out_shardings=(rep, rep),
        donate_argnums=(0,) if donate_state else (),
    )


def make_eval_forward(
    model,
    mesh: Mesh | None = None,
) -> Callable[[TrainState, jnp.ndarray], dict[str, jnp.ndarray]]:
    """Jitted inference forward: images → {cls_logits, box_deltas}.

    Uses running/frozen statistics (train=False).  With a mesh, the batch is
    sharded over ``data`` and outputs gathered — XLA inserts the all_gather
    (the reference ran eval on rank 0 only, SURVEY.md M10; here every chip
    contributes).
    """

    def forward(state: TrainState, images: jnp.ndarray):
        # uint8 batches normalize on device (data/pipeline.normalize_images).
        images = pipeline_lib.normalize_images(images)
        return model.apply(model_variables(state), images, train=False)

    if mesh is None:
        return jax.jit(forward)

    sharded = shard_map(
        forward,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)
