"""Training: state, SPMD step, loop.

Replaces the reference's L2/L3 training layers (SURVEY.md: Keras
``model.compile/fit_generator`` + ``hvd.DistributedOptimizer``) with a
functional JAX loop: an optax optimizer, an explicit TrainState pytree, and
ONE jit-compiled SPMD train step per shape bucket.
"""

from batchai_retinanet_horovod_coco_tpu.train.state import (
    TrainState,
    create_train_state,
    model_variables,
)
from batchai_retinanet_horovod_coco_tpu.train.step import make_eval_forward, make_train_step

__all__ = [
    "TrainState",
    "create_train_state",
    "make_eval_forward",
    "make_train_step",
    "model_variables",
]
