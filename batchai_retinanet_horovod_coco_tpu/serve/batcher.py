"""Per-bucket dynamic batcher: coalesce under a max-latency deadline.

One ``BucketBatcher`` thread per shape bucket pulls preprocessed requests
from its bounded queue and coalesces them into padded device-ready
batches:

- a batch FIRES when it reaches the bucket's largest compiled batch size,
  or when ``max_delay_ms`` has elapsed since its FIRST request arrived —
  the classic dynamic-batching deadline: under saturation batches fill
  instantly and the deadline never fires; under light load a lone request
  waits at most one deadline before running (padded, or at a smaller
  exported batch size when the engine has one);
- expired requests (per-request deadline) are rejected at collection time
  and never occupy a batch row;
- assembly reuses the input pipeline's pad template (`_pad_template`) and
  row layout (image at the top-left corner, dataset-mean pad margins) so
  a served image's batch row is byte-identical to the row the eval
  pipeline's ``_assemble`` would build — the other half of the
  bit-identity contract (router docstring has the resize half);
- the handoff to the dispatcher is a bounded stop-gated put: a slow
  device backpressures the batcher (watchdog ``idle()``, not a stall),
  and queue bounds upstream convert sustained overload into sheds.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    _pad_template,
    stop_gated_put,
)
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    AssembledBatch,
    RequestTimeout,
    ServeRequest,
    ServerClosed,
)


def assemble_requests(
    requests: list[ServeRequest],
    hw: tuple[int, int],
    batch_size: int,
) -> AssembledBatch:
    """Pad ≤``batch_size`` preprocessed requests into one device batch.

    Row layout matches ``data/pipeline._assemble`` exactly; surplus rows
    are whole pad-template slots with ``valid=False`` (the eval pipeline's
    short-batch semantics — discarded after NMS, invisible in results).
    """
    bh, bw = hw
    pad = _pad_template(bh, bw)
    images = np.empty((batch_size, bh, bw, 3), dtype=np.uint8)
    scales = np.ones((batch_size,), dtype=np.float32)
    valid = np.zeros((batch_size,), dtype=bool)
    for i, req in enumerate(requests):
        img = req.image
        h, w = img.shape[:2]
        images[i, :h, :w] = img
        if h < bh:
            images[i, h:] = pad[h:]
        if w < bw:
            images[i, :h, w:] = pad[:h, w:]
        scales[i] = req.scale
        valid[i] = True
    for i in range(len(requests), batch_size):
        images[i] = pad
    return AssembledBatch(
        hw=hw,
        images=images,
        requests=list(requests),
        scales=scales,
        valid=valid,
        t_assembled=monotonic_s(),
    )


class BucketBatcher:
    """One bucket's coalescing thread."""

    _POLL_S = 0.05

    def __init__(
        self,
        hw: tuple[int, int],
        engine,
        in_queue: queue.Queue,
        dispatch_queue: queue.Queue,
        max_delay_ms: float,
        on_reject: Callable[[ServeRequest, BaseException], None],
        on_fatal: Callable[[BaseException], None],
        stop: threading.Event,
    ):
        self.hw = hw
        self._engine = engine
        self._in = in_queue
        self._out = dispatch_queue
        self._max_delay_s = max(0.0, max_delay_ms) / 1e3
        self._on_reject = on_reject
        self._on_fatal = on_fatal
        self._stop = stop
        self.batches = 0
        self.deadline_fires = 0
        # watchdog: registers in _run() at thread start.
        self.thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"serve-batcher-{hw[0]}x{hw[1]}",
        )
        self.thread.start()

    def _take_live(self, timeout: float) -> ServeRequest | None:
        """Next non-expired request within ``timeout`` (expired ones are
        rejected in passing), else None."""
        deadline = monotonic_s() + timeout
        while True:
            remaining = deadline - monotonic_s()
            if remaining <= 0:
                return None
            try:
                req = self._in.get(timeout=min(remaining, self._POLL_S))
            except queue.Empty:
                if self._stop.is_set():
                    return None
                continue
            if req.expired():
                self._on_reject(req, RequestTimeout(
                    f"request {req.id} expired waiting for a batch"
                ))
                continue
            return req

    def _collect(self) -> list[ServeRequest] | None:
        """Block for a first request, then coalesce until full or the
        max-latency deadline; None when stopping with nothing taken."""
        first = None
        while first is None:
            if self._stop.is_set():
                return None
            first = self._take_live(self._POLL_S)
            self._hb.beat()
        max_b = self._engine.max_batch(self.hw)
        batch = [first]
        fire_at = monotonic_s() + self._max_delay_s
        while len(batch) < max_b:
            remaining = fire_at - monotonic_s()
            if remaining <= 0 or self._stop.is_set():
                self.deadline_fires += 1
                break
            req = self._take_live(remaining)
            if req is not None:
                batch.append(req)
        return batch

    def _run(self) -> None:
        self._hb = watchdog.register(
            f"serve-batcher-{self.hw[0]}x{self.hw[1]}",
            details=lambda: {
                "qsize": self._in.qsize(),
                "batches": self.batches,
            },
        )
        hb = self._hb
        try:
            while not self._stop.is_set():
                hb.beat()
                batch = self._collect()
                if not batch:
                    continue
                bsize = self._engine.batch_size_for(self.hw, len(batch))
                with trace.span(
                    "serve_assemble",
                    bucket=f"{self.hw[0]}x{self.hw[1]}",
                    n=len(batch),
                    padded_to=bsize,
                ):
                    assembled = assemble_requests(batch, self.hw, bsize)
                self.batches += 1
                hb.idle()  # a full dispatch queue is device backpressure
                if not stop_gated_put(self._out, assembled, self._stop):
                    for req in batch:
                        self._on_reject(
                            req, ServerClosed("server closed mid-batch")
                        )
                    return
                hb.beat()
        except BaseException as exc:
            self._on_fatal(exc)
        finally:
            hb.close()
