"""Per-bucket slot-pool batcher: continuous in-flight admission (ISSUE 14).

One ``BucketBatcher`` thread per shape bucket pulls preprocessed requests
from its bounded queue and claims them into a ``SlotPool`` — the batch
currently being ASSEMBLED.  A request admitted one tick after a batch
dispatched no longer waits a full deadline+round: it claims a free slot in
the assembling batch and rides the next seal.  The pool seals (assembles a
padded device batch and hands it to the dispatcher) when:

- it is FULL (every slot of the bucket's largest compiled batch claimed);
- the coalescing deadline (``max_delay_ms`` since the FIRST claim) fires —
  the classic dynamic-batching latency bound, alive in both modes;
- **continuous mode only**: the dispatch gate reports the device is ready
  (batch N's results just landed, or the device is idle) — a partial
  batch rides immediately instead of padding out the deadline, so the
  device never idles waiting for a "full" batch and a row's latency is
  bounded by one in-flight round.

``continuous=False`` (``ServeConfig``) keeps the deadline-only seal set
{full, deadline} — the pre-ISSUE-14 behavior, same slot pool underneath.

Other contracts, unchanged from the deadline-only ancestor:

- expired requests are rejected at claim time, and a claimed request
  whose deadline expires before the seal is EVICTED at the dispatch
  window — the eviction frees its slot atomically under the pool lock
  (an eviction racing the seal can never leave an orphaned claimed slot,
  nor a dead row riding the device);
- assembly reuses the input pipeline's pad template (`_pad_template`) and
  row layout (image at the top-left corner, dataset-mean pad margins) so
  a served image's batch row is byte-identical to the row the eval
  pipeline's ``_assemble`` would build — the other half of the
  bit-identity contract (router docstring has the resize half).  Slot
  timing changes WHEN rows ride, never what they compute (PARITY §5.9);
- the handoff to the dispatcher is a bounded stop-gated put: a slow
  device backpressures the batcher (watchdog ``idle()``, not a stall),
  and queue bounds upstream convert sustained overload into sheds.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    _pad_template,
    stop_gated_put,
)
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    AssembledBatch,
    RequestTimeout,
    ServeRequest,
    ServerClosed,
)


class SlotPool:
    """The batch currently being assembled for one bucket, slot-granular.

    ``claim()`` takes a free slot; ``seal()`` atomically evicts expired
    claims and takes every live row (resetting the pool) under ONE lock
    acquisition, so an expired-deadline eviction racing the dispatch
    window can neither orphan a claimed slot nor leak a dead row into
    the sealed batch.  ``now_fn`` is injectable for race-shaped tests
    (tests/unit/test_serve.py) — production uses the obs clock.
    """

    def __init__(self, capacity: int, now_fn: Callable[[], float] = monotonic_s):
        self.capacity = max(1, int(capacity))
        self._now = now_fn
        self._lock = make_lock("serve.batcher.SlotPool._lock")
        self._rows: list[ServeRequest] = []
        self._claim_t: list[float] = []
        self.first_claim_t: float | None = None
        self.evictions = 0

    def claim(self, req: ServeRequest) -> bool:
        """Claim one free slot for ``req``; False when the pool is full
        (the caller seals first, then re-claims)."""
        with self._lock:
            if len(self._rows) >= self.capacity:
                return False
            now = self._now()
            if not self._rows:
                self.first_claim_t = now
            self._rows.append(req)
            self._claim_t.append(now)
            return True

    def size(self) -> int:
        with self._lock:
            return len(self._rows)

    def free_slots(self) -> int:
        with self._lock:
            return self.capacity - len(self._rows)

    def fire_deadline(self, max_delay_s: float) -> float | None:
        """When the coalescing deadline fires for the current assembly
        (None while the pool is empty)."""
        with self._lock:
            if self.first_claim_t is None:
                return None
            return self.first_claim_t + max_delay_s

    def seal(
        self, on_evict: Callable[[ServeRequest, BaseException], None]
    ) -> tuple[list[ServeRequest], list[float]]:
        """Atomically evict expired claims, take every live row, reset.

        Returns ``(rows, slot_wait_ms)``, row-aligned.  Evicted requests
        are rejected with ``RequestTimeout`` AFTER the lock is released
        (callbacks must not run under the pool lock); their slots are
        already free by then — the no-orphaned-slot contract.
        """
        now = self._now()
        with self._lock:
            rows, waits, evicted = [], [], []
            for req, t in zip(self._rows, self._claim_t):
                if req.expired(now=now):
                    evicted.append(req)
                else:
                    rows.append(req)
                    waits.append((now - t) * 1e3)
            self._rows = []
            self._claim_t = []
            self.first_claim_t = None
            self.evictions += len(evicted)
        for req in evicted:
            on_evict(req, RequestTimeout(
                f"request {req.id} expired in its claimed slot"
            ))
        return rows, waits


def assemble_requests(
    requests: list[ServeRequest],
    hw: tuple[int, int],
    batch_size: int,
    slot_wait_ms: tuple = (),
) -> AssembledBatch:
    """Pad ≤``batch_size`` preprocessed requests into one device batch.

    Row layout matches ``data/pipeline._assemble`` exactly; surplus rows
    are whole pad-template slots with ``valid=False`` (the eval pipeline's
    short-batch semantics — discarded after NMS, invisible in results).
    """
    bh, bw = hw
    pad = _pad_template(bh, bw)
    images = np.empty((batch_size, bh, bw, 3), dtype=np.uint8)
    scales = np.ones((batch_size,), dtype=np.float32)
    valid = np.zeros((batch_size,), dtype=bool)
    for i, req in enumerate(requests):
        img = req.image
        h, w = img.shape[:2]
        images[i, :h, :w] = img
        if h < bh:
            images[i, h:] = pad[h:]
        if w < bw:
            images[i, :h, w:] = pad[:h, w:]
        scales[i] = req.scale
        valid[i] = True
    for i in range(len(requests), batch_size):
        images[i] = pad
    return AssembledBatch(
        hw=hw,
        images=images,
        requests=list(requests),
        scales=scales,
        valid=valid,
        t_assembled=monotonic_s(),
        slot_wait_ms=tuple(slot_wait_ms),
    )


class BucketBatcher:
    """One bucket's slot-pool admission thread."""

    _POLL_S = 0.05
    # While slots are claimed the loop polls tightly: a seal must notice
    # the dispatch gate / deadline within ~one device-dispatch overhead,
    # not within the idle poll.
    _ARMED_POLL_S = 0.002

    def __init__(
        self,
        hw: tuple[int, int],
        engine,
        in_queue: queue.Queue,
        dispatch_queue: queue.Queue,
        max_delay_ms: float,
        on_reject: Callable[[ServeRequest, BaseException], None],
        on_fatal: Callable[[BaseException], None],
        stop: threading.Event,
        gate=None,  # DispatchGate (continuous mode) or None (deadline-only)
    ):
        self.hw = hw
        self._engine = engine
        self._in = in_queue
        self._out = dispatch_queue
        self._max_delay_s = max(0.0, max_delay_ms) / 1e3
        self._on_reject = on_reject
        self._on_fatal = on_fatal
        self._stop = stop
        self._gate = gate
        self.pool = SlotPool(engine.max_batch(hw))
        self.batches = 0
        self.deadline_fires = 0
        self.full_fires = 0
        self.ready_fires = 0  # continuous seals: the device asked
        # watchdog: registers in _run() at thread start.
        self.thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"serve-batcher-{hw[0]}x{hw[1]}",
        )
        self.thread.start()

    def _take_live(self, timeout: float) -> ServeRequest | None:
        """Next non-expired request within ``timeout`` (expired ones are
        rejected in passing), else None."""
        deadline = monotonic_s() + timeout
        while True:
            remaining = deadline - monotonic_s()
            if remaining <= 0:
                return None
            try:
                req = self._in.get(timeout=min(remaining, self._POLL_S))
            except queue.Empty:
                if self._stop.is_set():
                    return None
                continue
            if req.expired():
                self._on_reject(req, RequestTimeout(
                    f"request {req.id} expired waiting for a batch"
                ))
                continue
            return req

    def _claim(self, req: ServeRequest) -> bool:
        """Claim + arm: the gate's armed flag tells the dispatcher that
        a post-fetch handoff wait can actually yield a batch."""
        ok = self.pool.claim(req)
        if ok and self._gate is not None:
            self._gate.arm(self.hw)
        return ok

    def _drain_claims(self) -> None:
        """Claim every immediately-available live request up to capacity
        — the last admission sweep before a seal ("up to the moment it
        dispatches")."""
        while self.pool.free_slots() > 0:
            try:
                req = self._in.get_nowait()
            except queue.Empty:
                return
            if req.expired():
                self._on_reject(req, RequestTimeout(
                    f"request {req.id} expired waiting for a batch"
                ))
                continue
            self._claim(req)

    def _seal_reason(self) -> str | None:
        """Why the assembling batch should seal NOW, or None.

        Deadline-only mode seals at {full, deadline}.  Continuous mode
        seals at {full, ready}: the gate is raised every time the device
        goes idle or a round's results land, so a claimed row waits at
        most ONE in-flight round — sealing at the deadline while work
        runs ahead would only freeze the batch partial without making
        any row ride sooner (the rows dispatch at the same instant
        either way, just in a smaller batch).  The deadline survives in
        continuous mode as a stall rescue (gate wedged = a bug, but the
        pool must never hold rows hostage to it) and as the drain flush.
        """
        n = self.pool.size()
        if n == 0:
            return None
        if n >= self.pool.capacity:
            return "full"
        now = monotonic_s()
        fire_at = self.pool.fire_deadline(self._max_delay_s)
        if self._gate is None:
            if fire_at is not None and now >= fire_at:
                return "deadline"
        else:
            if self._gate.is_ready() and self._out.empty():
                return "ready"
            # UNCONDITIONAL rescue: with multiple buckets sharing the
            # dispatch queue, a saturated sibling can keep it non-empty
            # indefinitely — past the rescue point this pool seals into
            # the queue regardless (the bounded stop-gated put is the
            # backpressure, exactly as in deadline-only mode), so a
            # claimed row is never held hostage to another bucket.
            rescue_at = (fire_at or now) + max(0.1, self._max_delay_s)
            if now >= rescue_at:
                return "deadline"
        if self._stop.is_set():
            return "deadline"  # draining: flush what is claimed
        return None

    def _seal_and_dispatch(self, hb, reason: str) -> bool:
        """Assemble the pool into a padded batch and hand it over;
        False when the server closed under the put."""
        self._drain_claims()
        if self.pool.size() >= self.pool.capacity:
            reason = "full"
        rows, waits = self.pool.seal(self._on_reject)
        if self._gate is not None:
            self._gate.disarm(self.hw)  # the pool is empty again
        if not rows:
            return True  # every claim expired — nothing rides
        if reason == "full":
            self.full_fires += 1
        elif reason == "ready":
            self.ready_fires += 1
            self._gate.clear()
        else:
            self.deadline_fires += 1
        bsize = self._engine.batch_size_for(self.hw, len(rows))
        with trace.span(
            "serve_assemble",
            bucket=f"{self.hw[0]}x{self.hw[1]}",
            n=len(rows),
            padded_to=bsize,
            reason=reason,
        ):
            assembled = assemble_requests(rows, self.hw, bsize, waits)
        self.batches += 1
        if trace.enabled():
            trace.counter(
                f"serve.occupancy.{self.hw[0]}x{self.hw[1]}",
                round(len(rows) / bsize, 4),
            )
        hb.idle()  # a full dispatch queue is device backpressure
        if not stop_gated_put(self._out, assembled, self._stop):
            for req in rows:
                self._on_reject(
                    req, ServerClosed("server closed mid-batch")
                )
            return False
        hb.beat()
        return True

    def _claim_timeout(self) -> float:
        """How long the claim phase may block on the in-queue before the
        seal conditions are re-checked."""
        n = self.pool.size()
        if n == 0:
            return self._POLL_S
        fire_at = self.pool.fire_deadline(self._max_delay_s)
        remaining = max(0.0, (fire_at or 0.0) - monotonic_s())
        if self._gate is not None:
            # Continuous: wake fast enough to catch the dispatch gate.
            return min(self._ARMED_POLL_S, remaining) or self._ARMED_POLL_S
        # Deadline-only: nothing to notice before the deadline but a
        # full pool, which the claim itself reports.
        return min(self._POLL_S, max(remaining, 1e-4))

    def _run(self) -> None:
        self._hb = watchdog.register(
            f"serve-batcher-{self.hw[0]}x{self.hw[1]}",
            details=lambda: {
                "qsize": self._in.qsize(),
                "claimed": self.pool.size(),
                "batches": self.batches,
            },
        )
        hb = self._hb
        try:
            while True:
                hb.beat()
                if self._stop.is_set():
                    return
                req = self._take_live(self._claim_timeout())
                if req is not None and not self._claim(req):
                    # Full pool racing an empty seal (every claim had
                    # expired): seal made room is the invariant — force
                    # one now, then the claim cannot fail.
                    if not self._seal_and_dispatch(hb, "full"):
                        self._on_reject(
                            req, ServerClosed("server closed mid-batch")
                        )
                        return
                    self._claim(req)
                reason = self._seal_reason()
                if reason is None:
                    continue
                if not self._seal_and_dispatch(hb, reason):
                    return
        except BaseException as exc:
            self._on_fatal(exc)
        finally:
            hb.close()
