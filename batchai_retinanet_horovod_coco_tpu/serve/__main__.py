"""``python -m batchai_retinanet_horovod_coco_tpu.serve`` → the serve CLI."""

from batchai_retinanet_horovod_coco_tpu.serve.frontend import main

if __name__ == "__main__":
    main()
