"""Dynamic-batching inference server over exported detectors (ISSUE 4).

The consumer of ``evaluate/export.py``'s StableHLO artifacts (and of live
params via the same compiled-detect path): requests are decoded/resized on
host worker threads with the input pipeline's own geometry, routed into
per-bucket SLOT POOLS (ISSUE 14: continuous in-flight batching — a
request claims a slot in the batch being assembled up to the moment it
dispatches, and a partial batch seals the instant the device is ready,
with the classic deadline-only coalescing kept as ``continuous=False``),
dispatched one-behind on device, and de-padded ROW BY ROW back to
per-request COCO-style detections that are bit-identical to
``run_coco_eval``'s (PARITY.md).

Layers (one module each; RUNBOOK §10 is the operator guide):

- ``common``   — config, request/future lifecycle, error taxonomy, stats
- ``engine``   — (bucket, batch) executable table + continuous one-behind
  dispatcher and the device-readiness ``DispatchGate``
- ``router``   — host preprocess workers (decode → resize → bucket-route)
- ``batcher``  — per-bucket slot-pool admission (continuous seal-on-ready
  or deadline-only coalescing)
- ``frontend`` — ``DetectionServer`` (admission/shedding/drain), the
  stdlib HTTP frontend, and the ``python -m …serve`` CLI
- ``replica``  — uniform replica handles (in-process / HTTP subprocess)
- ``fleet``    — ``FleetRouter``: health-weighted routing over N
  replicas, circuit breaking, fleet admission control, SLO-gated canary
  rollout (ISSUE 12; RUNBOOK §18), + the fleet HTTP frontend and the
  ``python -m …serve.fleet`` CLI
- ``stub``     — the canonical no-device stub engine (smoke/chaos/tests)
- ``stream``   — streaming video sessions over the slot pool (ISSUE 18;
  RUNBOOK §21): ordered per-stream frames with in-order delivery, IoU
  track stitching, and the frame-delta result cache
"""

from batchai_retinanet_horovod_coco_tpu.serve.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    LocalLauncher,
)
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    DetectionFuture,
    LatencyStats,
    RequestRejected,
    RequestTimeout,
    ServeConfig,
    ServeError,
    ServerClosed,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.serve.engine import DetectEngine
from batchai_retinanet_horovod_coco_tpu.serve.fleet import (
    FleetConfig,
    FleetRouter,
    serve_fleet_http,
)
from batchai_retinanet_horovod_coco_tpu.serve.frontend import (
    DetectionServer,
    serve_http,
)
from batchai_retinanet_horovod_coco_tpu.serve.replica import (
    HttpReplica,
    LocalReplica,
    ReplicaUnavailable,
)
from batchai_retinanet_horovod_coco_tpu.serve.stream import (
    StreamConfig,
    StreamManager,
    TrackStitcher,
)

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "DetectEngine",
    "DetectionServer",
    "DetectionFuture",
    "FleetConfig",
    "FleetRouter",
    "HttpReplica",
    "LatencyStats",
    "LocalLauncher",
    "LocalReplica",
    "ReplicaUnavailable",
    "RequestRejected",
    "RequestTimeout",
    "ServeConfig",
    "ServeError",
    "ServerClosed",
    "ServerError",
    "StreamConfig",
    "StreamManager",
    "TrackStitcher",
    "serve_fleet_http",
    "serve_http",
]
