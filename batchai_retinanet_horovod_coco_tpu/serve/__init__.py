"""Dynamic-batching inference server over exported detectors (ISSUE 4).

The consumer of ``evaluate/export.py``'s StableHLO artifacts (and of live
params via the same compiled-detect path): requests are decoded/resized on
host worker threads with the input pipeline's own geometry, routed into
per-bucket queues, coalesced into padded batches under a max-latency
deadline, dispatched one-behind on device, and de-padded back to
per-request COCO-style detections that are bit-identical to
``run_coco_eval``'s (PARITY.md).

Layers (one module each; RUNBOOK §10 is the operator guide):

- ``common``   — config, request/future lifecycle, error taxonomy, stats
- ``engine``   — (bucket, batch) executable table + one-behind dispatcher
- ``router``   — host preprocess workers (decode → resize → bucket-route)
- ``batcher``  — per-bucket coalescing under the latency deadline
- ``frontend`` — ``DetectionServer`` (admission/shedding/drain), the
  stdlib HTTP frontend, and the ``python -m …serve`` CLI
"""

from batchai_retinanet_horovod_coco_tpu.serve.common import (
    DetectionFuture,
    LatencyStats,
    RequestRejected,
    RequestTimeout,
    ServeConfig,
    ServeError,
    ServerClosed,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.serve.engine import DetectEngine
from batchai_retinanet_horovod_coco_tpu.serve.frontend import (
    DetectionServer,
    serve_http,
)

__all__ = [
    "DetectEngine",
    "DetectionServer",
    "DetectionFuture",
    "LatencyStats",
    "RequestRejected",
    "RequestTimeout",
    "ServeConfig",
    "ServeError",
    "ServerClosed",
    "ServerError",
    "serve_http",
]
