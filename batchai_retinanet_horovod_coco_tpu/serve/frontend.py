"""The serve frontend: admission control, request futures, stats, drain.

``DetectionServer`` wires the four serve machines together::

    submit(image) ──► admission queue (bounded; full ⇒ shed)
                        │  router workers: decode → resize → bucket
                        ▼
    per-bucket queues (bounded; full ⇒ shed)
                        │  BucketBatcher: coalesce under max_delay_ms
                        ▼
    dispatch queue (bounded; full ⇒ backpressure)
                        │  DeviceDispatcher: one-behind device dispatch
                        ▼
    fetch → detections_to_coco → per-request futures fulfilled

Contracts (pinned by tests/unit/test_serve.py):

- **Bit-identity**: a served image's detections are byte-for-byte the
  dicts ``run_coco_eval``'s sequential ``collect_detections`` produces
  for the same image — same resize (router), same batch row layout
  (batcher), same compiled program family (engine), same conversion
  (``detections_to_coco``, shared, not reimplemented).
- **Load shedding**: every queue is bounded; overload surfaces as
  ``RequestRejected(reason)`` at ``submit()`` or on the future — p99 of
  ACCEPTED requests stays bounded instead of the queue growing without
  limit.
- **Error propagation**: a crash in any serve thread fails every
  outstanding future with ``ServerError`` (original exception chained)
  and re-raises at the next ``submit()``/``result()`` — the shm
  pipeline's crash-re-raises-in-driver contract.
- **Graceful drain**: ``close()`` stops admission, waits (bounded) for
  in-flight requests to complete, then stops the threads; ``close()``
  never hangs and is idempotent.
- **Observability**: spans per stage (`serve_preprocess`,
  `serve_assemble`, `serve_dispatch`, `serve_fetch`, `serve_convert`)
  plus a cross-thread ``serve_request`` span per request; queue-depth
  counters; a watchdog heartbeat on every serve thread; periodic
  ``serve_stats`` events (p50/p99, sheds) into the obs event sink.
- **Live telemetry** (ISSUE 9): every server carries a pull-only
  metrics registry (``self.telemetry``, obs/telemetry.py — collectors
  over the same snapshot/LatencyStats the /stats payload reads, zero
  new hot-path work) exposed as ``GET /metrics`` (Prometheus text);
  ``GET /healthz`` is split from ``/stats`` and is TRUTHFUL — 503
  naming the stalled component whenever the watchdog registry reports
  a non-idle component past its stall budget — and carries the
  per-replica load fields the fleet router will weigh on.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
from typing import Any

import numpy as np

from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    detections_to_coco,
)
from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.serve.batcher import BucketBatcher
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    AssembledBatch,
    DetectionFuture,
    LatencyStats,
    OccupancyStats,
    RequestRejected,
    RequestTimeout,
    ServeConfig,
    ServeError,
    ServeRequest,
    ServerClosed,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.serve.engine import (
    DetectEngine,
    DeviceDispatcher,
    DispatchGate,
)
from batchai_retinanet_horovod_coco_tpu.serve.router import Router
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


class DetectionServer:
    """Dynamic-batching inference server over a ``DetectEngine``."""

    def __init__(
        self,
        engine: DetectEngine,
        config: ServeConfig = ServeConfig(),
        sink: Any = None,
        warmup: bool = True,
        replica_id: str | None = None,
    ):
        self.engine = engine
        self.config = config
        self.sink = sink
        # Stable identity for the fleet router / canary gate (ISSUE 12):
        # explicit (the fleet CLI pins it across restarts so the breaker
        # can re-admit "the same" replica), else host-pid — stable for
        # the server's lifetime, unique across a host's replicas.
        if replica_id is None:
            import os
            import socket

            replica_id = f"{socket.gethostname()}-{os.getpid()}"
        self.replica_id = replica_id
        self.stats = LatencyStats(window=config.latency_window)
        # The live-telemetry registry (ISSUE 9): pull-only — quantiles
        # read the LatencyStats window and the collector reads the same
        # snapshot() the /stats payload serves, all at scrape time, so
        # the request hot path pays nothing for /metrics existing.
        self.telemetry = telemetry.Registry()
        self.telemetry.histogram(
            "serve_request_latency_ms",
            "request latency over the recent window (accepted requests)",
            source=self.stats.window_ms,
        )
        # Slot-wait distribution (ISSUE 14): fed per dispatched batch in
        # _on_batch, exposed pull-only on THIS registry so both /metrics
        # surfaces carry it with no enable gating (the process-registry
        # twin, telemetry.record_serve_batch, is push-gated like the
        # train sites).
        self._slot_waits: list[float] = []
        self.telemetry.histogram(
            "serve_slot_wait_ms",
            "ms a claimed slot waited between claim and seal (continuous "
            "in-flight batching admission latency)",
            source=self._slot_wait_window,
        )
        self.telemetry.register_collector(self._telemetry_samples)
        self.telemetry.register_collector(telemetry.watchdog_collector())
        if warmup:
            engine.warmup()

        self._stop = threading.Event()
        self._lock = make_lock("serve.frontend.DetectionServer._lock")
        self._drained = threading.Condition(self._lock)
        self._outstanding: dict[int, ServeRequest] = {}
        self._error: BaseException | None = None
        self._accepting = True
        self._closed = False
        self._ids = itertools.count()
        self._batches_done = 0
        self.occupancy = OccupancyStats()

        self._admission: queue.Queue = queue.Queue(
            maxsize=max(1, config.admission_queue)
        )
        self._bucket_queues = {
            hw: queue.Queue(maxsize=max(1, config.bucket_queue))
            for hw in engine.buckets
        }
        self._dispatch_queue: queue.Queue = queue.Queue(
            maxsize=max(1, config.dispatch_depth)
        )
        # Continuous in-flight batching (ISSUE 14): the gate is the
        # device-readiness handshake partial batches seal against.
        self._gate = DispatchGate() if config.continuous else None
        self._router = Router(
            engine,
            self._admission,
            self._bucket_queues,
            on_reject=self._reject,
            on_fatal=self._fail,
            stop=self._stop,
            workers=config.preprocess_workers,
        )
        self._batchers = [
            BucketBatcher(
                hw,
                engine,
                self._bucket_queues[hw],
                self._dispatch_queue,
                config.max_delay_ms,
                on_reject=self._reject,
                on_fatal=self._fail,
                stop=self._stop,
                gate=self._gate,
            )
            for hw in engine.buckets
        ]
        self._dispatcher = DeviceDispatcher(
            engine,
            self._dispatch_queue,
            on_batch=self._on_batch,
            on_fatal=self._fail,
            stop=self._stop,
            gate=self._gate,
        )

    # ---- client surface --------------------------------------------------

    def submit(
        self,
        image,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> DetectionFuture:
        """Enqueue one image (HWC uint8 array or encoded bytes); returns a
        future.  Raises ``RequestRejected`` when shed at admission,
        ``ServerClosed`` after close, ``ServerError`` after a crash.

        ``trace_id`` (ISSUE 15) parents this request's ``serve_request``
        span under a fleet-wide trace: the span's args carry it (plus the
        replica id, so a merged fleet trace attributes every request span
        to its replica even where process labels are ambiguous) and a
        flow step links it to the fleet edge's span in Perfetto."""
        self._raise_pending()
        if timeout_s is None:
            timeout_s = self.config.default_timeout_s
        req = ServeRequest(
            next(self._ids),
            image,
            None if timeout_s is None else monotonic_s() + timeout_s,
            trace_id=trace_id,
        )
        if trace_id is None:
            req.span = trace.begin(
                "serve_request", id=req.id, replica=self.replica_id
            )
        else:
            req.span = trace.begin(
                "serve_request", id=req.id, replica=self.replica_id,
                trace=trace_id,
            )
            trace.flow_step("request", trace_id)
        # The accepting check and the registration must share ONE lock
        # acquisition: close()/_fail() flip _accepting and then reject
        # everything registered, so a request registered after a lock-free
        # check could slip in after the reject sweep and never resolve.
        with self._lock:
            if not self._accepting:
                self.stats.record_shed("shutting_down")
                trace.end(req.span)
                raise ServerClosed("server is draining/closed")
            self._outstanding[req.id] = req
        try:
            self._admission.put_nowait(req)
        except queue.Full:
            exc = RequestRejected("admission_queue_full")
            self._reject(req, exc)
            raise exc from None
        if trace.enabled():
            trace.counter("serve.admission_qsize", self._admission.qsize())
        return req.future

    def detect(self, image, timeout_s: float | None = None) -> list[dict]:
        """Blocking convenience: ``submit()`` + ``result()``."""
        return self.submit(image, timeout_s=timeout_s).result()

    def snapshot(self) -> dict:
        """Stats + live queue depths (the /stats endpoint payload)."""
        snap = self.stats.snapshot()
        with self._lock:
            snap["outstanding"] = len(self._outstanding)
        snap["admission_qsize"] = self._admission.qsize()
        snap["bucket_qsize"] = {
            f"{hw[0]}x{hw[1]}": q.qsize()
            for hw, q in self._bucket_queues.items()
        }
        snap["dispatch_qsize"] = self._dispatch_queue.qsize()
        snap["batches"] = self._batches_done
        snap["deadline_fires"] = sum(b.deadline_fires for b in self._batchers)
        snap["full_fires"] = sum(b.full_fires for b in self._batchers)
        snap["ready_fires"] = sum(b.ready_fires for b in self._batchers)
        snap["slot_evictions"] = sum(b.pool.evictions for b in self._batchers)
        snap["free_slots"] = self.free_slots()
        snap["slot_capacity"] = self.slot_capacity()
        occ = self.occupancy.snapshot()
        snap["occupancy_mean"] = occ.get("mean")
        snap["occupancy_last"] = occ.get("last")
        snap["continuous"] = self.config.continuous
        return snap

    def free_slots(self) -> int:
        """Unclaimed slots across every bucket's ASSEMBLING batch — the
        idle-capacity signal the fleet router steers on (ISSUE 14)."""
        return sum(b.pool.free_slots() for b in self._batchers)

    def _slot_wait_window(self) -> list[float]:
        with self._lock:
            return list(self._slot_waits)

    def slot_capacity(self) -> int:
        return sum(b.pool.capacity for b in self._batchers)

    def _telemetry_samples(self):
        """Scrape-time collector: the snapshot() fields as Prometheus
        families (counters for lifetime totals, gauges for live depths)."""
        snap = self.snapshot()
        yield ("serve_requests_completed_total", "counter",
               "requests completed successfully", None, snap["completed"])
        yield ("serve_requests_timeout_total", "counter",
               "requests that expired past their deadline", None,
               snap["timeouts"])
        yield ("serve_requests_failed_total", "counter",
               "requests failed by a server error", None, snap["failed"])
        for reason, n in sorted(snap["shed"].items()):
            yield ("serve_shed_total", "counter",
                   "requests shed by admission control, by reason",
                   {"reason": reason}, n)
        yield ("serve_batches_total", "counter",
               "device batches dispatched", None, snap["batches"])
        yield ("serve_deadline_fires_total", "counter",
               "partial batches fired by the coalescing deadline", None,
               snap["deadline_fires"])
        yield ("serve_ready_fires_total", "counter",
               "partial batches sealed by the dispatch gate (continuous "
               "in-flight batching)", None, snap["ready_fires"])
        yield ("serve_slot_evictions_total", "counter",
               "claimed slots freed by expired-deadline eviction at the "
               "dispatch window", None, snap["slot_evictions"])
        yield ("serve_free_slots", "gauge",
               "unclaimed slots across the assembling batches (idle "
               "device capacity the fleet router steers on)", None,
               snap["free_slots"])
        if snap["occupancy_mean"] is not None:
            yield ("serve_batch_occupancy_mean", "gauge",
                   "mean live-rows/batch-size over the recent batch "
                   "window", None, snap["occupancy_mean"])
            yield ("serve_batch_occupancy_last", "gauge",
                   "live-rows/batch-size of the last dispatched batch",
                   None, snap["occupancy_last"])
        yield ("serve_inflight", "gauge",
               "requests accepted and not yet resolved", None,
               snap["outstanding"])
        yield ("serve_queue_depth", "gauge", "live queue depths",
               {"queue": "admission"}, snap["admission_qsize"])
        yield ("serve_queue_depth", "gauge", "live queue depths",
               {"queue": "dispatch"}, snap["dispatch_qsize"])
        for bucket, depth in sorted(snap["bucket_qsize"].items()):
            yield ("serve_queue_depth", "gauge", "live queue depths",
                   {"queue": f"bucket_{bucket}"}, depth)
        yield ("serve_queue_capacity", "gauge",
               "configured queue bounds (the shed thresholds)",
               {"queue": "admission"}, max(1, self.config.admission_queue))
        yield ("serve_queue_capacity", "gauge",
               "configured queue bounds (the shed thresholds)",
               {"queue": "dispatch"}, max(1, self.config.dispatch_depth))

    def load_fields(self) -> dict:
        """The per-replica load summary the /healthz payload carries —
        shaped for the serve-fleet weighted router (ROADMAP): in-flight,
        queue depths vs bounds, and the windowed p99."""
        snap = self.snapshot()
        return {
            # Identity first (ISSUE 12): without these the fleet router
            # cannot attribute health, and the canary gate cannot tell
            # which export version a p99 regression belongs to.
            "replica_id": self.replica_id,
            "version": getattr(self.engine, "version", "live"),
            "inflight": snap["outstanding"],
            "admission_qsize": snap["admission_qsize"],
            "admission_capacity": max(1, self.config.admission_queue),
            "dispatch_qsize": snap["dispatch_qsize"],
            "bucket_qsize": snap["bucket_qsize"],
            "p99_ms": snap.get("p99_ms"),
            "completed": snap["completed"],
            "shed_total": snap["shed_total"],
            # Occupancy signals (ISSUE 14): free slots in the assembling
            # batches + recent mean batch occupancy — the fleet router
            # folds these into its weights so load steers at replicas
            # with idle device capacity.
            "free_slots": snap["free_slots"],
            "slot_capacity": snap["slot_capacity"],
            "occupancy": snap["occupancy_mean"],
            "accepting": self._accepting,
        }

    def close(self, drain: bool = True, timeout_s: float | None = None) -> None:
        """Stop accepting, optionally drain in-flight work, stop threads.

        Never hangs: the drain wait is bounded (``config.drain_timeout_s``
        unless overridden) and leftovers are rejected with
        ``ServerClosed``; idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._accepting = False
        if drain and self._error is None:
            budget = (
                self.config.drain_timeout_s if timeout_s is None else timeout_s
            )
            deadline = monotonic_s() + budget
            with self._drained:
                while self._outstanding:
                    remaining = deadline - monotonic_s()
                    if remaining <= 0:
                        break
                    self._drained.wait(timeout=min(remaining, 0.2))
        self._stop.set()
        self._reject_all(ServerClosed("server closed"))
        for t in (
            *self._router.threads,
            *(b.thread for b in self._batchers),
            self._dispatcher.thread,
        ):
            t.join(timeout=10)
        self._emit_stats(final=True)

    def __enter__(self) -> "DetectionServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))

    # ---- completion paths (any serve thread) -----------------------------

    def _finish(self, req: ServeRequest, *, result=None, error=None) -> bool:
        """Complete one request exactly once (both the fulfill and reject
        paths funnel here); False if it was already completed."""
        with self._lock:
            if self._outstanding.pop(req.id, None) is None:
                return False
            self._drained.notify_all()
        trace.end(req.span)
        if error is None:
            self.stats.record(monotonic_s() - req.t_submit)
            req.future._set_result(result)
        else:
            if isinstance(error, RequestRejected):
                self.stats.record_shed(error.reason)
            elif isinstance(error, RequestTimeout):
                self.stats.record_timeout()
            else:
                self.stats.record_failure()
            req.future._set_error(error)
        return True

    def _reject(self, req: ServeRequest, exc: BaseException) -> None:
        self._finish(req, error=exc)

    def _reject_all(self, exc: BaseException) -> None:
        with self._lock:
            pending = list(self._outstanding.values())
        for req in pending:
            self._finish(req, error=exc)

    def _fail(self, exc: BaseException) -> None:
        """Fatal error in any serve thread: record once, stop everything,
        fail every outstanding future (shm-pipeline crash contract)."""
        with self._lock:
            if self._error is None:
                self._error = exc
            self._accepting = False
        self._stop.set()
        wrapped = ServerError("serve worker thread crashed")
        wrapped.__cause__ = exc
        self._reject_all(wrapped)

    def _raise_pending(self) -> None:
        if self._error is not None:
            raise ServerError("serve worker thread crashed") from self._error

    # ---- batch completion (dispatcher thread) ----------------------------

    def _on_batch(self, assembled: AssembledBatch, det) -> None:
        reqs = assembled.requests
        n = assembled.images.shape[0]
        with trace.span(
            "serve_convert",
            bucket=f"{assembled.hw[0]}x{assembled.hw[1]}",
            n=len(reqs),
        ):
            # Per-row completion release (ISSUE 14): de-pad, convert, and
            # resolve ROW BY ROW — an early row's future resolves without
            # waiting on its bucket siblings' conversion.  The conversion
            # IS the eval path's ``detections_to_coco`` (rescale to
            # original coords, clamp to true bounds, drop degenerates),
            # called on single-row views; it is strictly per-row math, so
            # the slicing cannot change any result (PARITY §5.9).  Pad
            # rows (beyond len(reqs)) never convert at all.
            for i, req in enumerate(reqs):
                row = type(det)(
                    det.boxes[i:i + 1], det.scores[i:i + 1],
                    det.labels[i:i + 1], det.valid[i:i + 1],
                )
                dets = detections_to_coco(
                    row,
                    np.array([req.id], dtype=np.int64),
                    assembled.scales[i:i + 1],
                    assembled.valid[i:i + 1],
                    self.engine.label_to_cat_id,
                    image_sizes={req.id: req.orig_wh},
                )
                for d in dets:
                    d.pop("image_id", None)  # request-scoped; transport
                if req.expired():
                    self._finish(req, error=RequestTimeout(
                        f"request {req.id} finished after its deadline"
                    ))
                else:
                    self._finish(req, result=dets)
        self._batches_done += 1
        self.occupancy.record(len(reqs) / max(1, n))
        if assembled.slot_wait_ms:
            with self._lock:
                self._slot_waits.extend(assembled.slot_wait_ms)
                if len(self._slot_waits) > 4096:
                    del self._slot_waits[:-4096]
        if telemetry.enabled():
            # Args computed only on the enabled path: free_slots() takes
            # one lock per bucket pool — not a price the disabled hot
            # path pays (the callee's own gate is the second check).
            telemetry.record_serve_batch(
                occupancy=len(reqs) / max(1, n),
                free_slots=self.free_slots(),
                slot_wait_ms=assembled.slot_wait_ms,
            )
        if (
            self.sink is not None
            and self._batches_done % max(1, self.config.stats_every_batches)
            == 0
        ):
            self._emit_stats()

    def _emit_stats(self, final: bool = False) -> None:
        if self.sink is None:
            return
        try:
            self.sink.event(
                "serve_stats", final=final, **_flatten(self.snapshot())
            )
            # The full latency distribution record (p50/p90/p99/max over
            # the raw window) rides along for richer offline analysis.
            self.sink.histogram(
                "serve.request_latency", self.stats.window_ms()
            )
        except Exception:
            pass  # stats must never take the serving path down


def _flatten(snap: dict) -> dict:
    """Nested snapshot → JSONL-friendly flat fields."""
    out = {}
    for k, v in snap.items():
        if isinstance(v, dict):
            for kk, vv in v.items():
                out[f"{k}.{kk}"] = vv
        else:
            out[k] = v
    return out


# ---- stdlib HTTP frontend ------------------------------------------------


def serve_http(
    server: DetectionServer,
    host: str = "127.0.0.1",
    port: int = 0,
    request_timeout_s: float = 60.0,
    stream=None,
):
    """Wrap a ``DetectionServer`` in a stdlib ``ThreadingHTTPServer``.

    POST /detect   (body = encoded image)  → 200 JSON detections,
                   503 + reason on shed, 504 on deadline, 500 on crash
    POST /stream/open   (JSON {width?, height?}) → 200 {session, bucket}
    POST /stream/frame  (headers X-Retinanet-Stream + X-Retinanet-Frame,
                   optional X-Retinanet-Deadline-Ms; body = encoded
                   frame) → 200 {detections (with track_id), frame,
                   cache_hit}; 404 unknown session, 400 out-of-order /
                   bad input, 503 backlogged/shed, 504 deadline
                   (serve/stream.py — ISSUE 18)
    POST /stream/close  (header X-Retinanet-Stream) → 200 final stats
    GET  /stream   → 200 JSON per-stream status snapshot
    GET  /stats    → 200 JSON stats snapshot
    GET  /metrics  → 200 Prometheus text exposition (server.telemetry)
    GET  /healthz  → TRUTHFUL liveness, split from /stats (ISSUE 9
                   satellite — it used to be a cosmetic alias): 200 +
                   per-replica load fields while every watchdog
                   component is within budget, 503 naming the stalled
                   component otherwise (read-only probe; the watchdog
                   poll thread keeps its one-dump-per-stall latch)

    Request tracing (ISSUE 15): an ``X-Retinanet-Trace`` request header
    (minted here when absent) parents the request's ``serve_request``
    span; EVERY /detect response — success, shed, timeout, crash —
    echoes it back as the same header plus a ``trace_id`` JSON field, so
    a client or bench log can correlate a slow response with its span in
    the merged fleet trace.

    ``request_timeout_s`` bounds each handler's wait on its future — an
    HTTP client must never hang on a wedged pipeline (the watchdog names
    the wedge; the client gets a 504).  Returns the ``http.server``
    instance; the caller owns ``serve_forever()`` / ``shutdown()`` (the
    CLI below runs it).  The stream manager is created lazily on first
    streaming use and closed by ``server_close()``, so callers need no
    extra teardown step.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    # Streaming sessions ride the same frontend, but the manager (and
    # its delivery thread) is created lazily on the first /stream*
    # request: image-only servers never pay for it, and every existing
    # ``shutdown(); server_close()`` teardown stays leak-free because
    # ``server_close`` below also closes the manager if one was made.
    _stream_lock = make_lock("serve.frontend.serve_http._stream_lock")
    _stream_holder = [stream]

    def _stream():
        with _stream_lock:
            if _stream_holder[0] is None:
                from batchai_retinanet_horovod_coco_tpu.serve.stream import (
                    StreamManager,
                )

                _stream_holder[0] = StreamManager(server)
            return _stream_holder[0]

    class Handler(BaseHTTPRequestHandler):
        def _json(
            self, code: int, payload: dict, trace_id: str | None = None
        ) -> None:
            if trace_id is not None:
                payload = {**payload, "trace_id": trace_id}
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if trace_id is not None:
                self.send_header(trace.TRACE_HEADER, trace_id)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib casing)
            if self.path == "/stats":
                self._json(200, server.snapshot())
            elif self.path == "/healthz":
                code, payload = telemetry.healthz()
                payload["load"] = server.load_fields()
                self._json(code, payload)
            elif self.path == "/stream":
                self._json(200, _stream().status())
            elif self.path == "/metrics":
                body = server.telemetry.prometheus_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": "not_found"})

        def _stream_rejected(self, exc, trace_id):
            """The stream flavor of the taxonomy → status-code mapping:
            a dead/unknown session is 404 (re-open, don't retry), client
            protocol faults (bad input, out-of-order frame) are 400,
            everything transient is 503."""
            if exc.reason == "unknown_stream":
                code = 404
            elif exc.reason in ("decode_error", "stream_out_of_order"):
                code = 400
            else:
                code = 503
            self._json(
                code, {"error": "rejected", "reason": exc.reason},
                trace_id=trace_id,
            )

        def _do_stream(self, trace_id):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                if self.path == "/stream/open":
                    spec = json.loads(body) if body else {}
                    out = _stream().open_stream(
                        width=spec.get("width"),
                        height=spec.get("height"),
                        trace_id=trace_id,
                    )
                    self._json(200, out, trace_id=trace_id)
                elif self.path == "/stream/frame":
                    sid = self.headers.get("X-Retinanet-Stream", "")
                    try:
                        seq = int(self.headers.get("X-Retinanet-Frame", -1))
                        deadline_ms = self.headers.get(
                            "X-Retinanet-Deadline-Ms"
                        )
                        timeout_s = (
                            float(deadline_ms) / 1e3
                            if deadline_ms else None
                        )
                    except ValueError:
                        # A malformed header is the client's fault: 400
                        # via the taxonomy mapping, not a dropped
                        # connection.
                        raise RequestRejected(
                            "decode_error", "malformed stream header"
                        ) from None
                    fut = _stream().submit_frame(
                        sid, seq, body,
                        timeout_s=timeout_s,
                        trace_id=trace_id,
                    )
                    dets = fut.result(timeout=request_timeout_s)
                    self._json(
                        200,
                        {
                            "detections": dets,
                            "frame": seq,
                            "cache_hit": bool(
                                getattr(fut, "cache_hit", False)
                            ),
                        },
                        trace_id=trace_id,
                    )
                elif self.path == "/stream/close":
                    sid = self.headers.get("X-Retinanet-Stream", "")
                    stats = _stream().close_stream(sid)
                    self._json(
                        200, {"closed": sid, "stats": stats},
                        trace_id=trace_id,
                    )
                else:
                    self._json(404, {"error": "not_found"})
            except RequestRejected as exc:
                self._stream_rejected(exc, trace_id)
            except (RequestTimeout, TimeoutError):
                self._json(
                    504, {"error": "deadline_exceeded"}, trace_id=trace_id
                )
            except ServeError as exc:
                self._json(
                    500, {"error": "server_error", "detail": str(exc)},
                    trace_id=trace_id,
                )
            except Exception as exc:
                # Same catch-all the fleet frontend carries: an
                # unexpected handler fault answers 500 instead of
                # closing the connection mid-request.
                self._json(
                    500, {"error": "server_error", "detail": str(exc)},
                    trace_id=trace_id,
                )

        def do_POST(self):  # noqa: N802
            if self.path.startswith("/stream/"):
                trace_id = (
                    self.headers.get(trace.TRACE_HEADER)
                    or trace.new_trace_id()
                )
                self._do_stream(trace_id)
                return
            if self.path != "/detect":
                self._json(404, {"error": "not_found"})
                return
            # The propagated fleet trace id (minted here for direct
            # clients) — every response branch echoes it (ISSUE 15).
            trace_id = (
                self.headers.get(trace.TRACE_HEADER) or trace.new_trace_id()
            )
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                dets = server.submit(body, trace_id=trace_id).result(
                    timeout=request_timeout_s
                )
            except RequestRejected as exc:
                # The taxonomy distinction in status codes: a bad INPUT is
                # the client's fault and not retryable (400); shed load is
                # transient and retryable (503).
                code = 400 if exc.reason == "decode_error" else 503
                self._json(
                    code, {"error": "rejected", "reason": exc.reason},
                    trace_id=trace_id,
                )
            except (RequestTimeout, TimeoutError):
                self._json(
                    504, {"error": "deadline_exceeded"}, trace_id=trace_id
                )
            except ServeError as exc:
                self._json(
                    500, {"error": "server_error", "detail": str(exc)},
                    trace_id=trace_id,
                )
            else:
                self._json(200, {"detections": dets}, trace_id=trace_id)

        def log_message(self, *args) -> None:
            pass  # request logging is the stats/obs layer's job

    class _ServeHTTPServer(ThreadingHTTPServer):
        # ``stream_manager`` creates on first touch (same lazy path the
        # handlers use); ``server_close`` tears down whatever exists so
        # the standard ``shutdown(); server_close()`` teardown never
        # leaks the delivery thread.
        @property
        def stream_manager(self):
            return _stream()

        def server_close(self):
            with _stream_lock:
                mgr = _stream_holder[0]
            if mgr is not None:
                mgr.close()
            super().server_close()

    return _ServeHTTPServer((host, port), Handler)


# ---- CLI -----------------------------------------------------------------


def build_parser():
    import argparse

    from batchai_retinanet_horovod_coco_tpu.utils.cli import (
        add_obs_flags,
        add_serve_flags,
    )

    p = argparse.ArgumentParser(
        description="Serve an exported detector (convert_model.py output) "
                    "over HTTP, or run it over a directory of images.",
    )
    p.add_argument("--export-dir", default=None,
                   help="export directory (manifest.json + .stablehlo "
                        "artifacts) from convert_model.py; required "
                        "unless --stub-engine")
    p.add_argument("--stub-engine", action="store_true",
                   help="serve the stub engine instead of an export: no "
                        "device work, one fixed detection per request — "
                        "the fleet smoke / chaos harness replica "
                        "(serve/stub.py)")
    p.add_argument("--stub-delay-ms", type=float, default=0.0,
                   help="stub engine per-dispatch delay (simulated "
                        "device time; lets harnesses shape p99)")
    p.add_argument("--stub-video", action="store_true",
                   help="stub engine video mode (ISSUE 18): each row's "
                        "boxes derive from that row's pixel brightness, "
                        "so seeded drift footage yields deterministic "
                        "drifting boxes — the streaming smoke/tests "
                        "replica")
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--http", type=int, metavar="PORT",
                      help="start the HTTP frontend on this port "
                           "(0 = ephemeral; serves until interrupted)")
    mode.add_argument("--images", metavar="DIR",
                      help="offline mode: submit every image in DIR, "
                           "write detections JSONL, print stats, exit")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--output", default=None,
                   help="offline mode: detections JSONL path "
                        "(default: stdout summary only)")
    p.add_argument("--platform", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="backend to serve on (same flag surface as "
                        "convert_model.py / train.py)")
    add_serve_flags(p)
    add_obs_flags(p)
    return p


def main(argv: list[str] | None = None) -> dict:
    import os
    import signal

    args = build_parser().parse_args(argv)

    if args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)

    from batchai_retinanet_horovod_coco_tpu.utils.cli import (
        configure_obs,
        make_serve_config,
    )

    # Replica-labeled process track in the merged fleet trace (ISSUE 15):
    # the per-process trace file and its Perfetto process group carry the
    # replica id, not a generic "serve".
    process_label = getattr(args, "replica_id", None) or "serve"
    obs_dir = configure_obs(args, process_label=process_label)
    # Fleet-spawned replicas join the parent's RETINANET_OBS_DIR export
    # contract (the shm-worker mechanism): tracing self-enables under the
    # parent's run id, this process exports its own trace fragment at
    # exit, and the fleet CLI's finalize merges it onto the fleet
    # timeline.  Explicit --obs-trace/--obs-dir flags win.
    joined_env = obs_dir is None and trace.maybe_configure_from_env(
        process_label
    )
    # The fleet CLI stops replicas with SIGTERM: exit through the same
    # finally as an interrupt so the trace fragment is exported and the
    # server drains instead of dying mid-request.
    def _on_sigterm(_signum, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    if args.stub_engine:
        from batchai_retinanet_horovod_coco_tpu.serve.stub import (
            StubDetectEngine,
        )

        engine = StubDetectEngine(
            delay_s=args.stub_delay_ms / 1e3, video=args.stub_video
        )
    elif args.export_dir is None:
        raise SystemExit("--export-dir is required (or pass --stub-engine)")
    else:
        engine = DetectEngine.from_export(args.export_dir)
    print(
        f"engine: buckets={engine.buckets} "
        f"batch_sizes={ {hw: engine.batch_sizes(hw) for hw in engine.buckets} } "
        f"resize={engine.min_side}/{engine.max_side} "
        f"version={getattr(engine, 'version', 'live')}"
    )
    sink = None
    if obs_dir is not None:
        # serve_stats / watchdog_stall / slo_violation events land in
        # metrics.jsonl next to the trace (the perf doctor's events half).
        from batchai_retinanet_horovod_coco_tpu.obs.events import EventSink

        sink = EventSink(obs_dir, run_config=vars(args))
        watchdog.default().sink = sink
    server = DetectionServer(
        engine, make_serve_config(args), sink=sink,
        replica_id=getattr(args, "replica_id", None),
    )
    slo_monitor = None
    status_server = None
    try:
        # Telemetry/SLO bring-up INSIDE the try: a typo'd --slo-rule or
        # an already-bound --obs-port must still drain the server and
        # close the sink on the way out.  Same policy as train.py's
        # _start_telemetry: either flag starts the monitor (the built-in
        # stall rule is always included).
        if (
            obs_dir is not None
            or getattr(args, "slo_rule", None)
            or getattr(args, "obs_port", None) is not None
        ):
            # Arm the push-path record sites (telemetry.record_serve_batch
            # → the process default registry) whenever observability is
            # on — the same policy as train.py's _start_telemetry.
            telemetry.enable()
        if (
            getattr(args, "slo_rule", None)
            or getattr(args, "obs_port", None) is not None
        ):
            from batchai_retinanet_horovod_coco_tpu.obs import slo as slo_lib

            slo_monitor = slo_lib.SloMonitor(
                server.telemetry,
                [slo_lib.stall_rule()]
                + [slo_lib.parse_rule(s) for s in (args.slo_rule or [])],
                sink=sink,
                poll_interval=args.slo_poll_s,
            ).start()
        if getattr(args, "obs_port", None) is not None:
            # A second, serve-path-independent scrape port (the offline
            # --images mode has no HTTP frontend; on --http it lets the
            # scraper live apart from request traffic).
            status_server = telemetry.start_http_server(
                server.telemetry, port=args.obs_port, host=args.host
            )
            print(
                f"telemetry on http://{status_server.host}:"
                f"{status_server.port} (/metrics /healthz /statusz)"
            )
        if args.images is not None:
            names = sorted(
                n for n in os.listdir(args.images)
                if n.lower().endswith((".jpg", ".jpeg", ".png", ".bmp"))
            )
            # The offline client is a polite one: on an admission shed it
            # BLOCKS on its oldest in-flight future and retries, instead
            # of crashing — a directory larger than the admission queue
            # must drain completely, not trip the overload protection.
            futures: list[tuple[str, object]] = []
            drained = 0
            records = []

            def drain_one():
                nonlocal drained
                name, fut = futures[drained]
                drained += 1
                try:
                    records.append({"file": name, "detections": fut.result()})
                except ServeError as exc:
                    records.append({"file": name, "error": str(exc)})

            for name in names:
                with open(os.path.join(args.images, name), "rb") as f:
                    payload = f.read()
                while True:
                    try:
                        futures.append((name, server.submit(payload)))
                        break
                    except RequestRejected:
                        if drained >= len(futures):
                            raise  # nothing in flight to wait on
                        drain_one()
            while drained < len(futures):
                drain_one()
            if args.output:
                # Atomic: downstream tooling ingests this JSONL by name;
                # publish it complete or not at all — streamed, so a big
                # offline batch never materializes twice in memory.
                from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
                    atomic_writer,
                )

                with atomic_writer(args.output) as f:
                    for rec in records:
                        f.write(json.dumps(rec) + "\n")
                print(f"wrote {len(records)} records to {args.output}")
        else:
            httpd = serve_http(server, args.host, args.http)
            print(
                f"serving on http://{httpd.server_address[0]}:"
                f"{httpd.server_address[1]} (POST /detect /stream/*; "
                "GET /stats /stream /metrics /healthz)"
            )
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                httpd.shutdown()
                httpd.server_close()  # also closes the stream manager
        snap = server.snapshot()
        print(json.dumps({"serve_stats": snap}))
        return snap
    finally:
        if slo_monitor is not None:
            slo_monitor.stop()
        if status_server is not None:
            status_server.close()
        server.close()
        if sink is not None:
            sink.close()
        if obs_dir is not None:
            from batchai_retinanet_horovod_coco_tpu import obs

            obs.finalize()
        elif joined_env:
            # Env-joined (fleet-spawned) replica: export THIS process's
            # fragment only — the fleet parent owns the merge.
            trace.export()


if __name__ == "__main__":
    main()
