"""Autoscaling control plane for the serve fleet (ISSUE 19).

The fleet so far runs a FIXED replica set that only a human resizes.
Every signal a control loop needs already exists on the fleet registry
(occupancy, free slots, federated p99, shed counters — ISSUE 14/15) and
every actuator exists too (subprocess spawn + respawn supervision from
ISSUE 12, drain-on-SIGTERM from the serve frontend, the breaker's
half-open readmit).  This module is ONLY the loop that connects them:

- ``AutoscalePolicy`` — the declarative contract: a target occupancy
  band (hysteresis: no decision inside it), an optional federated-p99
  ceiling, min/max replica bounds (``min_replicas=0`` ⇒ scale-to-zero
  for cold tiers), per-direction cooldowns, and step sizes.  Loadable
  from a JSON policy file (``AutoscalePolicy.from_file``).
- ``Autoscaler`` — the slo.py-shaped evaluator: ``check_once(now=...)``
  on an injectable clock (the whole anti-flap state machine is testable
  without sleeping), a watchdog-registered poll thread with the
  crash-announce contract, and ONE structured ``autoscale_decision``
  event per decision (trace instant + sink record + stderr JSONL line —
  the fleet router's emit layering) carrying the reason, the signal
  values it acted on, and the replica delta.

Decision semantics:

- **Scale-up** fires after the occupancy-high (or p99-ceiling) breach
  holds ``for_s`` AND the up-cooldown has elapsed — exactly one decision
  per cooldown window while the breach sustains.  New replicas join
  through the admission gate every newcomer passes (ISSUE 12): the
  launcher blocks until ``/healthz`` answers 200, and the router gives
  the replica weight only after its OWN first successful health poll —
  the same probe contract a half-open breaker readmit uses, so a sick
  spawn never takes traffic.  At ``max_replicas`` the breach still emits
  a (capped) decision — that event is what ``obs/analyze --fleet`` ranks
  as ``fleet:underprovisioned``.
- **Scale-down** picks the LOWEST-weight routable replica the launcher
  owns, marks it draining in the router (``begin_drain`` — no new
  traffic, pinned streams re-pin on their next frame, the replica drops
  out of the occupancy aggregates), and SIGTERMs it into the serve
  frontend's drain path; the slot is reclaimed only once the launcher
  reports the process gone with in-flight zero (``reap``).  In-flight
  work is never dropped.
- **Scale-to-zero** (``min_replicas=0``) requires STRICT idleness — no
  completions, no sheds, zero in-flight, zero open streams for the
  sustained window.  A request arriving at an empty fleet sheds
  ``no_replica_available`` at the edge; that shed delta is the demand
  signal that scales 1 replica up IMMEDIATELY (no sustain, no cooldown
  — an empty fleet recovering is never flap), so the first client retry
  after the spawn lands.
- **Preemption** (a replica dying un-asked) is free scale-down: the
  respawn supervision readmits it through the breaker's half-open probe,
  and when the respawn budget is exhausted (``utils/backoff.py``) the
  abandoned slot is pruned here (``launcher.prune``) and ordinary
  policy evaluation repairs capacity on the next tick.

The launcher is duck-typed (the fleet CLI's subprocess launcher and the
in-process ``LocalLauncher`` below both satisfy it):

- ``launch() -> replica``  — spawn one replica, blocking until healthy;
- ``terminate(replica_id)`` — begin an orderly shutdown (SIGTERM);
- ``reap(replica_id) -> bool`` — True once fully gone (port reclaimed);
- ``owns(replica_id) -> bool`` — may this replica be scaled down?
- ``prune() -> list[str]``    — abandoned slots (respawn budget spent).

Scaling never alters per-request results (PARITY §5.20): the loop adds
and removes capacity; routing, batching, and the engine are untouched.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import threading
from typing import Any, Callable

from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.events import emit_event
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock

#: The federated-snapshot key whose per-poll increase signals demand at
#: an EMPTY fleet (a request shed because no replica was routable).
_DEMAND_KEY = 'fleet_shed_total{reason="no_replica_available"}'


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The declarative scaling contract (frozen; a policy change is a
    new policy object).  The occupancy band is a hysteresis band: above
    ``occupancy_high`` (sustained) scales up, below ``occupancy_low``
    (sustained) scales down, and INSIDE the band no decision ever fires
    — oscillating load between the thresholds produces zero decisions."""

    min_replicas: int = 1  # 0 = scale-to-zero (cold tier)
    max_replicas: int = 4
    occupancy_low: float = 0.25
    occupancy_high: float = 0.75
    # Optional federated-p99 SLO ceiling (ms): a sustained breach scales
    # up even while occupancy reads inside the band (queueing shows up
    # in latency before slot occupancy saturates).
    p99_slo_ms: float | None = None
    scale_up_step: int = 1
    scale_down_step: int = 1
    # A breach must hold this long before ANY decision fires.
    for_s: float = 5.0
    # Per-direction cooldowns: at most one decision per direction per
    # window while a breach sustains.
    up_cooldown_s: float = 10.0
    down_cooldown_s: float = 30.0
    # Poll cadence of the production thread (check_once is injectable).
    interval_s: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError(
                f"min_replicas must be >= 0, got {self.min_replicas}"
            )
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"max_replicas must be >= max(1, min_replicas), got "
                f"{self.max_replicas} (min {self.min_replicas})"
            )
        if not 0.0 <= self.occupancy_low < self.occupancy_high <= 1.0:
            raise ValueError(
                "need 0 <= occupancy_low < occupancy_high <= 1, got "
                f"[{self.occupancy_low}, {self.occupancy_high}]"
            )
        if self.scale_up_step < 1 or self.scale_down_step < 1:
            raise ValueError("scale steps must be >= 1")
        for field in ("for_s", "up_cooldown_s", "down_cooldown_s"):
            if getattr(self, field) < 0:
                raise ValueError(f"{field} must be >= 0")

    @classmethod
    def from_json(cls, doc: dict) -> "AutoscalePolicy":
        """Build from a policy-file document; unknown keys are an error
        (a typo'd knob silently falling back to its default is exactly
        how a production policy lies)."""
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise ValueError(
                f"unknown autoscale policy keys {unknown}; known: "
                f"{sorted(known)}"
            )
        return cls(**doc)

    @classmethod
    def from_file(cls, path: str) -> "AutoscalePolicy":
        with open(path) as f:
            return cls.from_json(json.load(f))


class Autoscaler:
    """The control loop: reads ``router.federated_snapshot()`` +
    ``router.status()``, decides against the policy, actuates through
    the launcher.  ``check_once(now=...)`` returns the decisions fired
    this tick (usually empty); ``start()`` runs it on a
    watchdog-registered poll thread in production."""

    MAX_KEPT = 1000  # bounded decision history, like SloMonitor

    def __init__(self, router, policy: AutoscalePolicy, launcher,
                 sink: Any | None = None):
        self.router = router
        self.policy = policy
        self.launcher = launcher
        self.sink = sink if sink is not None else getattr(
            router, "sink", None
        )
        self.decisions: list[dict] = []
        self._lock = make_lock("serve.autoscale.Autoscaler._lock")
        self._draining: dict[str, float] = {}  # rid -> drain start
        self._up_since: float | None = None
        self._down_since: float | None = None
        self._last_up_t = float("-inf")
        self._last_down_t = float("-inf")
        self._ups = 0
        self._downs = 0
        self._capped = 0
        self._desired = 0
        self._spawn_seq = 0
        self._last_snap: dict[str, float] = {}
        self._last_signals: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Keep ONE bound-method object: attribute access mints a fresh
        # one each time, and unregister_collector matches by identity.
        self._collector = self._collect
        router.telemetry.register_collector(self._collector)

    # ---- metrics ---------------------------------------------------------

    def _collect(self):
        with self._lock:
            desired, draining = self._desired, len(self._draining)
            ups, downs, capped = self._ups, self._downs, self._capped
        yield ("fleet_replicas_desired", "gauge",
               "replica count the autoscale policy currently wants",
               None, float(desired))
        yield ("fleet_replicas_active", "gauge",
               "non-drained replicas the autoscaler counts as capacity",
               None, float(self.router.active_replica_count()))
        yield ("fleet_scale_up_total", "counter",
               "autoscale scale-up decisions", None, float(ups))
        yield ("fleet_scale_down_total", "counter",
               "autoscale scale-down decisions", None, float(downs))
        yield ("fleet_scale_capped_total", "counter",
               "scale-up breaches blocked at max_replicas (the "
               "fleet:underprovisioned signal)", None, float(capped))
        yield ("fleet_autoscale_draining", "gauge",
               "replicas currently draining toward removal", None,
               float(draining))

    # ---- one tick --------------------------------------------------------

    def check_once(self, now: float | None = None) -> list[dict]:
        """One evaluation: reap finished drains, prune abandoned slots,
        read the signals, fire at most one decision.  Injectable ``now``
        pins the sustain/cooldown machinery in tests."""
        now = monotonic_s() if now is None else now
        pol = self.policy
        self._finish_drains()
        for rid in self.launcher.prune():
            self.router.remove_replica(rid)

        snap = self.router.federated_snapshot()
        status = self.router.status()
        states = status["replicas"]
        active = sum(1 for r in states if r["state"] != "drained")
        occupancy = snap.get("fleet_occupancy")
        p99 = snap.get("fleet_federated_p99_ms")
        if p99 is None:
            # Without a federation scrape this tick, the health-poll
            # advertised worst replica p99 is the same ceiling input.
            p99 = snap.get("fleet_replica_p99_ms")
        with self._lock:
            prev = self._last_snap
            self._last_snap = snap
        # Labeled shed counters only materialize on their first
        # increment, so a key missing from a non-empty baseline IS a
        # zero baseline — the first-ever ``no_replica_available`` shed
        # must register as demand.  An empty prev (first tick) stays 0.
        if prev:
            demand = max(
                0.0,
                float(snap.get(_DEMAND_KEY) or 0.0)
                - float(prev.get(_DEMAND_KEY) or 0.0),
            )
        else:
            demand = 0.0
        completed = self._delta(
            prev, snap, "fleet_requests_completed_total"
        )
        inflight = snap.get("fleet_inflight") or 0.0
        streams = snap.get("fleet_streams_open") or 0.0
        idle = (
            completed == 0.0 and demand == 0.0
            and inflight == 0.0 and streams == 0.0
        )
        signals = {
            "occupancy": None if occupancy is None else round(occupancy, 4),
            "p99_ms": None if p99 is None else round(float(p99), 3),
            "inflight": inflight,
            "streams_open": streams,
            "demand_shed": demand,
            "active": active,
        }
        with self._lock:
            self._last_signals = signals

        fired: list[dict] = []
        decision = self._decide(now, active, occupancy, p99, idle,
                                demand, signals, states)
        if decision is not None:
            fired.append(decision)
        with self._lock:
            self._desired = min(
                pol.max_replicas,
                max(pol.min_replicas,
                    self.router.active_replica_count()),
            )
        return fired

    def _decide(self, now, active, occupancy, p99, idle, demand,
                signals, states) -> dict | None:
        pol = self.policy
        # Immediate paths — bypass sustain AND cooldown: capacity below
        # the declared floor (or demand hitting an empty fleet) is a
        # contract violation, never flap.
        if active == 0 and demand > 0:
            return self._scale_up(
                now, max(1, pol.min_replicas), "demand_scale_from_zero",
                signals, sustained_s=0.0,
            )
        if active < pol.min_replicas:
            return self._scale_up(
                now, pol.min_replicas - active, "below_min", signals,
                sustained_s=0.0,
            )

        up_reason = None
        if occupancy is not None and occupancy > pol.occupancy_high:
            up_reason = "occupancy_high"
        elif (pol.p99_slo_ms is not None and p99 is not None
              and float(p99) > pol.p99_slo_ms):
            up_reason = "p99_breach"
        if up_reason is not None:
            self._down_since = None
            if self._up_since is None:
                self._up_since = now
            sustained = now - self._up_since
            if sustained < pol.for_s or now - self._last_up_t < pol.up_cooldown_s:
                return None
            if active >= pol.max_replicas:
                # The breach the policy cannot act on: one capped
                # decision per cooldown window — the underprovisioned
                # evidence trail.
                self._last_up_t = now
                with self._lock:
                    self._capped += 1
                return self._emit_decision(
                    decision="scale_up_capped", reason=up_reason,
                    delta=0, active=active, signals=signals,
                    sustained_s=round(sustained, 3),
                )
            step = min(pol.scale_up_step, pol.max_replicas - active)
            return self._scale_up(now, step, up_reason, signals,
                                  sustained_s=round(sustained, 3))

        down_breach = (
            occupancy is not None and occupancy < pol.occupancy_low
        )
        if not down_breach:
            self._up_since = None
            self._down_since = None
            return None
        self._up_since = None
        if self._down_since is None:
            self._down_since = now
        sustained = now - self._down_since
        # The LAST replica goes only on strict idleness: a trickle of
        # traffic below the band keeps one replica alive even at min 0.
        floor = pol.min_replicas if (pol.min_replicas >= 1 or idle) else 1
        if (
            sustained < pol.for_s
            or now - self._last_down_t < pol.down_cooldown_s
            or active <= floor
        ):
            return None
        step = min(pol.scale_down_step, active - floor)
        return self._scale_down(
            now, step, "idle" if idle else "occupancy_low", signals,
            sustained_s=round(sustained, 3), states=states,
        )

    # ---- actuation -------------------------------------------------------

    def _scale_up(self, now, count, reason, signals, sustained_s):
        self._last_up_t = now
        launched, errors = 0, 0
        for _ in range(count):
            try:
                replica = self.launcher.launch()
            except Exception as exc:
                errors += 1
                self._emit_event(
                    "autoscale_launch_failed", error=repr(exc)[:300]
                )
                continue
            self.router.add_replica(replica)
            launched += 1
        if launched:
            with self._lock:
                self._ups += 1
        return self._emit_decision(
            decision="scale_up", reason=reason, delta=launched,
            active=signals["active"], signals=signals,
            sustained_s=sustained_s,
            **({"launch_errors": errors} if errors else {}),
        )

    def _scale_down(self, now, count, reason, signals, sustained_s,
                    states):
        victims = self._pick_victims(states, count)
        if not victims:
            return None  # nothing the launcher owns — no decision
        self._last_down_t = now
        for rid in victims:
            self.router.begin_drain(rid)
            self.launcher.terminate(rid)
            self._draining[rid] = now
        with self._lock:
            self._downs += 1
        return self._emit_decision(
            decision="scale_down", reason=reason, delta=-len(victims),
            active=signals["active"], signals=signals,
            sustained_s=sustained_s, victims=victims,
        )

    def _pick_victims(self, states, count) -> list[str]:
        """Lowest-weight routable replicas the launcher owns (a canary
        under evaluation and attached foreign replicas are never scaled
        down).  Weight ties break on replica_id, like routing does."""
        cands = sorted(
            (
                (r["weight"], r["replica_id"])
                for r in states
                if r["state"] == "closed" and not r["is_canary"]
                and self.launcher.owns(r["replica_id"])
            ),
        )
        return [rid for _w, rid in cands[:count]]

    def _finish_drains(self) -> None:
        for rid in sorted(self._draining):
            if self.launcher.reap(rid):
                self._draining.pop(rid, None)
                self.router.remove_replica(rid)

    # ---- events ----------------------------------------------------------

    def _emit_decision(self, *, decision, reason, delta, active,
                       signals, sustained_s, **extra) -> dict:
        record = {
            "decision": decision,
            "reason": reason,
            "delta": delta,
            "replicas_before": active,
            "sustained_s": sustained_s,
            **{k: v for k, v in signals.items() if k != "active"},
            **extra,
        }
        self.decisions.append(record)
        if len(self.decisions) > self.MAX_KEPT:
            del self.decisions[: -self.MAX_KEPT]
        self._emit_event("autoscale_decision", **record)
        return record

    def _emit_event(self, kind: str, **fields) -> None:
        """The fleet emit layering (ISSUE 15): trace instant + sink
        record + ONE serialized stderr JSONL line per event — shared
        implementation in obs.events.emit_event (ISSUE 20)."""
        emit_event(kind, sink=self.sink, **fields)

    @staticmethod
    def _delta(prev: dict, snap: dict, key: str) -> float:
        """Per-tick increase of a cumulative counter key; 0 on the first
        sample (no baseline) — the SloMonitor delta-rule convention."""
        cur = snap.get(key)
        if cur is None:
            return 0.0
        base = prev.get(key)
        if base is None:
            return 0.0
        return max(0.0, float(cur) - float(base))

    # ---- status + lifecycle ----------------------------------------------

    def status(self) -> dict:
        """The /fleet debugging view of the loop's live state."""
        with self._lock:
            return {
                "policy": dataclasses.asdict(self.policy),
                "desired": self._desired,
                "draining": sorted(self._draining),
                "signals": dict(self._last_signals),
                "scale_ups": self._ups,
                "scale_downs": self._downs,
                "capped": self._capped,
                "breaching_up": self._up_since is not None,
                "breaching_down": self._down_since is not None,
                "decisions_tail": self.decisions[-5:],
            }

    def _run(self, hb: watchdog.Heartbeat) -> None:
        try:
            while not self._stop.wait(self.policy.interval_s):
                hb.beat()
                self.check_once()
        except BaseException as e:
            # Crash channel (thread-error-contract): a silently dead
            # autoscaler means capacity frozen at its last decision —
            # announce on stderr, re-raise so the thread death is loud.
            print(
                json.dumps(
                    {"event": "autoscaler_crashed", "error": repr(e)}
                ),
                file=sys.stderr, flush=True,
            )
            raise
        finally:
            hb.close()

    def start(self) -> "Autoscaler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        hb = watchdog.register("fleet-autoscaler")
        self._thread = threading.Thread(
            target=self._run, args=(hb,), daemon=True,
            name="fleet-autoscaler",
        )
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Signal the poll loop without joining (safe from the poll
        thread itself — the SloMonitor contract)."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Detach the gauges: a stopped control loop reporting frozen
        # desired/active counts on a live fleet registry would lie.
        self.router.telemetry.unregister_collector(self._collector)


class LocalLauncher:
    """In-process launcher over ``LocalReplica`` handles — the unit-test
    and bench actuator (the fleet CLI uses its subprocess launcher).

    ``factory(replica_id)`` builds one replica handle; ``terminate`` is
    deliberately lazy (the router's ``begin_drain`` already unroutes the
    victim) and ``reap`` performs the BOUNDED drain: in-flight work on
    the victim completes before the slot is reclaimed — the zero-drop
    contract the scale-down tests pin."""

    def __init__(self, factory: Callable[[str], Any],
                 drain_timeout_s: float = 10.0, prefix: str = "scale"):
        self._factory = factory
        self._drain_timeout_s = drain_timeout_s
        self._prefix = prefix
        self._seq = 0
        self._live: dict[str, Any] = {}
        self._terminating: set[str] = set()

    def launch(self):
        rid = f"{self._prefix}-{self._seq}"
        self._seq += 1
        replica = self._factory(rid)
        self._live[rid] = replica
        return replica

    def adopt(self, replica) -> None:
        """Register a pre-existing replica as launcher-owned, so the
        seed replicas a harness builds by hand are scale-down eligible."""
        self._live[replica.replica_id] = replica

    def owns(self, rid: str) -> bool:
        return rid in self._live

    def terminate(self, rid: str) -> None:
        self._terminating.add(rid)

    def reap(self, rid: str) -> bool:
        if rid not in self._terminating:
            return False
        replica = self._live.get(rid)
        if replica is None:
            self._terminating.discard(rid)
            return True
        # Bounded drain: lets in-flight futures complete, then closes.
        replica.drain(timeout_s=self._drain_timeout_s)
        server = getattr(replica, "server", None)
        if server is not None and getattr(server, "_outstanding", 0):
            return False  # still draining — try again next tick
        try:
            replica.close()
        except Exception:
            pass  # release is best-effort; the handle is already out
        self._live.pop(rid, None)
        self._terminating.discard(rid)
        return True

    def prune(self) -> list[str]:
        return []


__all__ = ["AutoscalePolicy", "Autoscaler", "LocalLauncher"]
