"""The canonical stub engine: serve machinery without device work.

One fixed detection per batch row, an optional per-dispatch delay, and a
record of dispatched batch sizes — everything the queue/batcher/frontend
machinery needs to run for real while the "device" costs nothing.  It
existed as two drifting private copies (tests/unit/test_serve.py and
scripts/telemetry_smoke.py) before the fleet work (ISSUE 12) needed a
THIRD: subprocess stub replicas for ``make fleet-smoke`` and the chaos
serve leg (``python -m …serve --stub-engine``).  Now there is one.

The fixed detection round-trips ``detections_to_coco`` exactly:
``EXPECTED_DETECTIONS`` is what any 64x64 request served through a stub
engine must come back as — the assertion constant for every consumer.
"""

from __future__ import annotations

import time  # lint-exempt rationale below: injected dispatch delay only

import numpy as np

from batchai_retinanet_horovod_coco_tpu.serve.engine import IdentityLabelMap


class StubDetections:
    """Duck-typed Detections (boxes/scores/labels/valid attrs)."""

    def __init__(self, boxes, scores, labels, valid):
        self.boxes, self.scores, self.labels = boxes, scores, labels
        self.valid = valid


#: What one stub-served 64x64 request resolves to, after the shared
#: ``detections_to_coco`` conversion (xyxy → xywh, clamped).
EXPECTED_DETECTIONS = [
    {"category_id": 0, "bbox": [1.0, 2.0, 9.0, 18.0], "score": 0.5}
]


class StubDetectEngine:
    """One fixed detection per row; records dispatched batch sizes.

    ``delay_s`` makes the "device" slow enough that bounded queues shed
    under an open-loop flood (the telemetry smoke's requirement) or that
    a canary's p99 visibly regresses (the fleet chaos leg's requirement).
    """

    min_side = 64
    max_side = 64
    buckets = ((64, 64),)
    label_to_cat_id = IdentityLabelMap()
    source = "stub"

    def __init__(
        self,
        batch_sizes: tuple[int, ...] = (4,),
        delay_s: float = 0.0,
        version: str = "stub",
    ):
        self._sizes = sorted(batch_sizes)
        self.delay_s = delay_s
        self.version = version
        self.dispatched: list[int] = []

    def batch_sizes(self, hw):
        return list(self._sizes)

    def max_batch(self, hw):
        return self._sizes[-1]

    def batch_size_for(self, hw, n):
        for b in self._sizes:
            if b >= n:
                return b
        return self._sizes[-1]

    def warmup(self):
        pass

    def dispatch(self, hw, images):
        if self.delay_s:
            # The injected "device time" — a plain sleep, deliberately
            # not the obs clock (nothing here is a timestamp).
            time.sleep(self.delay_s)
        b = images.shape[0]
        self.dispatched.append(b)
        boxes = np.tile(
            np.array([[[1.0, 2.0, 10.0, 20.0]]], np.float32), (b, 1, 1)
        )
        return StubDetections(
            boxes,
            np.full((b, 1), 0.5, np.float32),
            np.zeros((b, 1), np.int32),
            np.ones((b, 1), bool),
        )

    def fetch(self, det):
        return det


__all__ = ["EXPECTED_DETECTIONS", "StubDetectEngine", "StubDetections"]
