"""The canonical stub engine: serve machinery without device work.

One fixed detection per batch row, an optional per-dispatch delay, and a
record of dispatched batch sizes — everything the queue/batcher/frontend
machinery needs to run for real while the "device" costs nothing.  It
existed as two drifting private copies (tests/unit/test_serve.py and
scripts/telemetry_smoke.py) before the fleet work (ISSUE 12) needed a
THIRD: subprocess stub replicas for ``make fleet-smoke`` and the chaos
serve leg (``python -m …serve --stub-engine``).  Now there is one.

The fixed detection round-trips ``detections_to_coco`` exactly:
``EXPECTED_DETECTIONS`` is what any 64x64 request served through a stub
engine must come back as — the assertion constant for every consumer.
"""

from __future__ import annotations

import time  # lint-exempt rationale below: injected dispatch delay only

import numpy as np

from batchai_retinanet_horovod_coco_tpu.serve.engine import IdentityLabelMap


class StubDetections:
    """Duck-typed Detections (boxes/scores/labels/valid attrs)."""

    def __init__(self, boxes, scores, labels, valid):
        self.boxes, self.scores, self.labels = boxes, scores, labels
        self.valid = valid


#: What one stub-served 64x64 request resolves to, after the shared
#: ``detections_to_coco`` conversion (xyxy → xywh, clamped).
EXPECTED_DETECTIONS = [
    {"category_id": 0, "bbox": [1.0, 2.0, 9.0, 18.0], "score": 0.5}
]


class StubDetectEngine:
    """One fixed detection per row; records dispatched batch sizes.

    ``delay_s`` makes the "device" slow enough that bounded queues shed
    under an open-loop flood (the telemetry smoke's requirement) or that
    a canary's p99 visibly regresses (the fleet chaos leg's requirement).
    """

    min_side = 64
    max_side = 64
    buckets = ((64, 64),)
    label_to_cat_id = IdentityLabelMap()
    source = "stub"

    def __init__(
        self,
        batch_sizes: tuple[int, ...] = (4,),
        delay_s: float = 0.0,
        version: str = "stub",
        video: bool = False,
    ):
        self._sizes = sorted(batch_sizes)
        self.delay_s = delay_s
        self.version = version
        self.video = video
        self.dispatched: list[int] = []

    def batch_sizes(self, hw):
        return list(self._sizes)

    def max_batch(self, hw):
        return self._sizes[-1]

    def batch_size_for(self, hw, n):
        for b in self._sizes:
            if b >= n:
                return b
        return self._sizes[-1]

    def warmup(self):
        pass

    def dispatch(self, hw, images):
        if self.delay_s:
            # The injected "device time" — a plain sleep, deliberately
            # not the obs clock (nothing here is a timestamp).
            time.sleep(self.delay_s)
        b = images.shape[0]
        self.dispatched.append(b)
        if self.video:
            return self._dispatch_video(images)
        boxes = np.tile(
            np.array([[[1.0, 2.0, 10.0, 20.0]]], np.float32), (b, 1, 1)
        )
        return StubDetections(
            boxes,
            np.full((b, 1), 0.5, np.float32),
            np.zeros((b, 1), np.int32),
            np.ones((b, 1), bool),
        )

    def _dispatch_video(self, images):
        """Video mode (ISSUE 18): each row's boxes are a pure function of
        THAT ROW's pixels (mean brightness → box offset), so serving a
        ``drift_frames`` sequence yields deterministic, smoothly-drifting
        boxes regardless of how rows land in batches — batch-invariant by
        construction, which is exactly the bit-identity contract the
        streaming PARITY pin (§5.19) leans on.  Two boxes per row with
        distinct categories give the track stitcher a real 2×2 matching
        problem every frame."""
        b = images.shape[0]
        boxes = np.zeros((b, 2, 4), np.float32)
        for r in range(b):
            m = np.float32(images[r].mean())
            dx = m * np.float32(0.2)  # ≤ ~36px inside the 64px bucket
            dy = m * np.float32(0.1)
            boxes[r, 0] = [1.0 + dx, 2.0 + dx, 10.0 + dx, 20.0 + dx]
            boxes[r, 1] = [30.0 + dy, 28.0 + dy, 44.0 + dy, 50.0 + dy]
        return StubDetections(
            np.clip(boxes, 0.0, 64.0),
            np.tile(np.array([[0.5, 0.4]], np.float32), (b, 1)),
            np.tile(np.array([[0, 1]], np.int32), (b, 1)),
            np.ones((b, 2), bool),
        )

    def fetch(self, det):
        return det


def drift_frames(
    seed: int = 0,
    n: int = 30,
    hw: tuple[int, int] = (64, 64),
    step: float = 1.0,
    cut_every: int = 0,
) -> list[np.ndarray]:
    """A seeded synthetic video: ``n`` uniform-brightness HWC uint8
    frames whose value drifts by ``step`` per frame (so the mean-abs
    pixel delta between consecutive frames is ≈ ``step`` — the delta
    cache's hit/miss dial), with an optional hard "scene cut" every
    ``cut_every`` frames (a large jump: guaranteed cache miss AND a
    track break).  Pure function of ``seed`` — the streaming tests,
    smoke, and SERVEBENCH leg all replay identical footage."""
    rng = np.random.default_rng(seed)
    v = float(rng.integers(30, 90))
    frames = []
    for i in range(n):
        if cut_every and i and i % cut_every == 0:
            # Jump to the opposite brightness band: the cut's delta is
            # ≥ 30 counts no matter where the drift had wandered.
            if v < 100.0:
                v = float(rng.integers(130, 170))
            else:
                v = float(rng.integers(10, 50))
        elif i:
            v += step
        v = min(175.0, max(10.0, v))
        frames.append(
            np.full((hw[0], hw[1], 3), int(round(v)), np.uint8)
        )
    return frames


__all__ = [
    "EXPECTED_DETECTIONS",
    "StubDetectEngine",
    "StubDetections",
    "drift_frames",
]
