"""Preprocess router: decode → resize → bucket-route, on host worker threads.

The serve twin of the input pipeline's decode stage: raw requests (encoded
image bytes or decoded HWC uint8 arrays) are decoded and resized on host
CPU worker threads, then routed into the per-bucket queues the dynamic
batcher coalesces from.

Geometry is NOT re-implemented here: ``bucket_for_source`` and
``resize_for_bucket`` (data/pipeline.py) are the single source of truth
shared with the train/eval pipeline, so a served image lands in exactly
the bucket — resized to exactly the pixels — that ``run_coco_eval`` would
have produced for it.  That is what makes the served detections
bit-identical to the offline eval path (PARITY.md, pinned by
tests/unit/test_serve.py).

Failure routing is per-request: a bad payload (undecodable bytes, wrong
dtype/rank) rejects THAT request with ``decode_error`` and the worker
moves on; only an unexpected crash of the worker loop itself escalates to
``on_fatal`` (the frontend then fails loudly — shm error contract).
"""

from __future__ import annotations

import io
import queue
import threading
from typing import Callable

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    bucket_for_source,
    resize_for_bucket,
)
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    RequestRejected,
    RequestTimeout,
    ServeRequest,
)


def decode_payload(payload) -> np.ndarray:
    """Request payload → HWC uint8 RGB array (the pipeline's decode
    contract: ``PIL.Image.open(...).convert("RGB")``, identical to
    ``load_example``'s, so encoded bytes of a dataset image decode to the
    same pixels the eval pipeline saw)."""
    if isinstance(payload, np.ndarray):
        if payload.ndim != 3 or payload.shape[2] != 3:
            raise ValueError(f"expected HWC RGB array, got {payload.shape}")
        if payload.dtype != np.uint8:
            raise ValueError(f"expected uint8 pixels, got {payload.dtype}")
        return payload
    if isinstance(payload, (bytes, bytearray, memoryview)):
        from PIL import Image

        with Image.open(io.BytesIO(payload)) as im:
            return np.asarray(im.convert("RGB"), dtype=np.uint8)
    raise ValueError(f"unsupported payload type {type(payload).__name__}")


class Router:
    """``preprocess_workers`` threads pulling from the admission queue."""

    _POLL_S = 0.1

    def __init__(
        self,
        engine,
        admission_queue: queue.Queue,
        bucket_queues: dict[tuple[int, int], queue.Queue],
        on_reject: Callable[[ServeRequest, BaseException], None],
        on_fatal: Callable[[BaseException], None],
        stop: threading.Event,
        workers: int = 2,
    ):
        self._engine = engine
        self._in = admission_queue
        self._buckets = bucket_queues
        self._on_reject = on_reject
        self._on_fatal = on_fatal
        self._stop = stop
        # watchdog: each worker registers in _run() at thread start.
        self.threads = [
            threading.Thread(
                target=self._run, daemon=True, name=f"serve-preprocess-{i}"
            )
            for i in range(max(1, workers))
        ]
        for t in self.threads:
            t.start()

    def _preprocess(self, req: ServeRequest) -> None:
        """One request: decode → bucket pick → resize → route (or shed)."""
        if req.expired():
            self._on_reject(req, RequestTimeout(
                f"request {req.id} expired before preprocessing"
            ))
            return
        try:
            with trace.span("serve_preprocess"):
                image = decode_payload(req.payload)
                h, w = image.shape[:2]
                bucket = bucket_for_source(
                    h, w, self._engine.min_side, self._engine.max_side,
                    self._engine.buckets,
                )
                resized, scale = resize_for_bucket(
                    image, bucket, self._engine.min_side,
                    self._engine.max_side,
                )
        except Exception as exc:  # bad input, not a broken server
            self._on_reject(
                req, RequestRejected("decode_error", repr(exc))
            )
            return
        req.payload = None  # the raw bytes are dead weight from here on
        req.image = resized
        req.scale = np.float32(scale)
        req.orig_wh = (w, h)
        req.bucket = bucket
        q = self._buckets[bucket]
        try:
            q.put_nowait(req)  # bounded: full bucket queue = shed, not wait
        except queue.Full:
            self._on_reject(req, RequestRejected("bucket_queue_full"))
            return
        if trace.enabled():
            trace.counter(
                f"serve.bucket_qsize.{bucket[0]}x{bucket[1]}", q.qsize()
            )

    def _run(self) -> None:
        # Beats on every poll; only a WEDGED decode/resize stops the
        # heartbeat (and gets named by the watchdog).
        hb = watchdog.register(
            "serve-preprocess",
            details=lambda: {"admission_qsize": self._in.qsize()},
        )
        try:
            while not self._stop.is_set():
                hb.beat()
                try:
                    req = self._in.get(timeout=self._POLL_S)
                except queue.Empty:
                    continue
                self._preprocess(req)
        except BaseException as exc:
            self._on_fatal(exc)
        finally:
            hb.close()
