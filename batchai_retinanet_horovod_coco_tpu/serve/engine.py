"""Executable table + one-behind device dispatch (the serve device layer).

``DetectEngine`` owns one compiled detection program per (shape bucket,
batch size) and nothing else — the TVM lesson (PAPERS.md): a compiled
static-shape program is the deployable unit, and serving is routing into a
small table of them.  Two constructors:

- ``from_export(dir)`` — load a ``convert_model.py`` export directory
  (evaluate/export.py): self-contained StableHLO artifacts, params baked
  in, NO model code needed.  Routing metadata (buckets, batch sizes,
  resize rule, label→category mapping) comes from the manifest.
- ``from_state(model, state, ...)`` — live params, AOT-compiled through
  the same ``evaluate.detect.compile_detect_fn`` path the eval bench
  uses, so a serve executable can never drift from the benched one.

Both AOT-build every executable at construction and ``warmup()`` runs
each once on zeros — no request ever pays a compile (SURVEY.md §7.3's
static-shape price is paid exactly once, at startup).

``DeviceDispatcher`` is the single device-facing thread: it pulls
assembled batches from a bounded queue and dispatches ONE-BEHIND — batch
N is dispatched before batch N−1's results are pulled, so the host-side
``device_get`` + conversion of N−1 overlap N's forward+NMS on device (the
``evaluate/detect.py`` eval-driver overlap trick, request-path edition).
When the queue runs dry the pending batch is fetched immediately, so the
overlap never costs latency under light load.

In continuous mode (ISSUE 14) the one-behind seam grows into a loop
around a ``DispatchGate``: whenever the device will take the next batch
immediately — it is idle, or the dispatcher is about to block fetching
the only in-flight batch — the gate is set, and the bucket batchers seal
their ASSEMBLING partial batch against it instead of waiting out the
coalescing deadline.  A batch sealed during batch N's fetch is dispatched
the instant N's results land, BEFORE N's conversion, so the device hop
N → N+1 never waits on host-side convert work.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import stop_gated_put
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.serve.common import AssembledBatch
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


class IdentityLabelMap(dict):
    """label → category fallback when no mapping is known (CSV-style
    datasets where labels ARE the category ids)."""

    def __missing__(self, key: int) -> int:
        return key


class DetectEngine:
    """A (bucket, batch) → compiled-program table with routing metadata."""

    def __init__(
        self,
        fns: dict[tuple[int, int], dict[int, Callable]],
        min_side: int,
        max_side: int,
        label_to_cat_id: dict[int, int] | None = None,
        source: str = "live",
        version: str = "live",
    ):
        if not fns:
            raise ValueError("engine needs at least one (bucket, batch) program")
        self._fns = fns
        self.min_side = min_side
        self.max_side = max_side
        self.label_to_cat_id = (
            label_to_cat_id if label_to_cat_id else IdentityLabelMap()
        )
        self.source = source
        # The model/rollout identity the fleet router and canary gate
        # attribute weight by (ISSUE 12): the export manifest's recorded
        # version, the export dir's basename as a fallback on legacy
        # manifests, or "live" for from_state engines.
        self.version = version
        self.buckets: tuple[tuple[int, int], ...] = tuple(sorted(fns))

    # ---- table lookups ---------------------------------------------------

    def batch_sizes(self, hw: tuple[int, int]) -> list[int]:
        return sorted(self._fns[hw])

    def max_batch(self, hw: tuple[int, int]) -> int:
        return max(self._fns[hw])

    def batch_size_for(self, hw: tuple[int, int], n: int) -> int:
        """Smallest compiled batch size that fits ``n`` requests (a lone
        straggler runs at batch 1 when exported); the largest otherwise —
        the batcher never forms more than ``max_batch`` requests."""
        sizes = self.batch_sizes(hw)
        for b in sizes:
            if b >= n:
                return b
        return sizes[-1]

    # ---- device ----------------------------------------------------------

    def dispatch(self, hw: tuple[int, int], images: np.ndarray):
        """Asynchronously dispatch one padded batch; returns device
        Detections (fetch with ``fetch``)."""
        return self._fns[hw][images.shape[0]](images)

    def fetch(self, det):
        """Block until a dispatched batch finishes; numpy Detections."""
        import jax

        return jax.device_get(det)

    def warmup(self) -> None:
        """Run every (bucket, batch) program once on zeros and sync — the
        startup AOT warm that keeps compiles/deserialization-autotune out
        of the request path."""
        import jax

        for hw in self.buckets:
            for b in self.batch_sizes(hw):
                with trace.span(
                    "serve_warmup", bucket=f"{hw[0]}x{hw[1]}", batch=b
                ):
                    jax.block_until_ready(
                        self.dispatch(hw, np.zeros((b, *hw, 3), np.uint8))
                    )

    # ---- constructors ----------------------------------------------------

    @classmethod
    def from_export(cls, export_dir: str) -> "DetectEngine":
        """Engine over a ``convert_model.py`` export directory — needs only
        jax, never the model code or the checkpoint."""
        from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
            load_model,
        )

        from batchai_retinanet_horovod_coco_tpu.ops.nms import Detections

        loaded = load_model(export_dir)
        fns: dict[tuple[int, int], dict[int, Callable]] = {}
        for b, h, w in loaded.buckets():
            raw = loaded.fn(b, (h, w))

            # Exported programs return a bare (boxes, scores, labels,
            # valid) tuple (jax.export flattens the NamedTuple); restore
            # the Detections view the conversion path expects.
            def call(images, _raw=raw):
                return Detections(*_raw(images))

            fns.setdefault((h, w), {})[b] = call
        manifest = loaded.manifest
        raw_map = manifest.get("label_to_cat_id")
        label_map = (
            {int(k): int(v) for k, v in raw_map.items()} if raw_map else None
        )
        buckets = sorted(fns)
        # Legacy manifests predate the recorded resize rule; falling back
        # to the bucket extents keeps routing sane (every image fits SOME
        # bucket) while new exports carry the exact eval-time sides.
        min_side = manifest.get("image_min_side") or min(
            min(hw) for hw in buckets
        )
        max_side = manifest.get("image_max_side") or max(
            max(hw) for hw in buckets
        )
        import os

        version = manifest.get("version") or os.path.basename(
            os.path.normpath(export_dir)
        )
        return cls(
            fns, min_side, max_side, label_map, source=export_dir,
            version=str(version),
        )

    @classmethod
    def from_state(
        cls,
        model,
        state,
        buckets: tuple[tuple[int, int], ...] | None = None,
        batch_sizes: tuple[int, ...] | None = None,
        config=None,
        min_side: int = 800,
        max_side: int = 1333,
        label_to_cat_id: dict[int, int] | None = None,
        mesh=None,
    ) -> "DetectEngine":
        """Engine over live params, AOT-compiled via the shared
        ``compile_detect_fn`` path (one executable per bucket × batch).

        ``batch_sizes=None`` resolves each bucket's executable table from
        the per-device schedule registry (tune/schedule.py ``serve.
        batch_sizes``; built-in default ``(8,)`` for untuned buckets — an
        unknown device falls back with one loud structured event).  The
        NMS impl/block/``pre_nms_size`` knobs resolve the same way inside
        ``compile_detect_fn`` (evaluate/detect.resolve_detect_config).
        The registry lookup is cached for the process lifetime, so every
        program is compiled at startup and no request ever recompiles.
        An explicit tuple pins every bucket to those sizes.
        """
        from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
            default_buckets,
        )
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            compile_detect_fn,
        )
        from batchai_retinanet_horovod_coco_tpu.tune import (
            serve_batch_sizes_for,
        )

        if buckets is None:
            buckets = default_buckets(min_side, max_side)
        if config is None:
            config = DetectConfig()
        fns: dict[tuple[int, int], dict[int, Callable]] = {}
        for hw in buckets:
            sizes = (
                serve_batch_sizes_for(hw, (8,))
                if batch_sizes is None
                else batch_sizes
            )
            fns[hw] = {
                b: compile_detect_fn(model, state, hw, b, config, mesh=mesh)
                for b in sorted(set(sizes))
            }
        return cls(fns, min_side, max_side, label_to_cat_id, source="live")


class DispatchGate:
    """The device-readiness handshake between the dispatcher and the
    bucket batchers (continuous mode, ISSUE 14).

    Two signals cross it:

    - **ready** (dispatcher → batchers): the next sealed batch will be
      dispatched immediately — the device is idle, or batch N's results
      just landed.  SET by the dispatcher, CLEARED by whoever consumes
      it (the batcher that seals against it / the dispatcher when a
      batch arrives).  Batchers seal their assembling partial batch the
      moment they see it, so N+1 rides the instant N returns instead of
      padding out the coalescing deadline.
    - **armed** (batchers → dispatcher): at least one bucket pool has
      claimed slots.  The dispatcher uses it to decide whether a brief
      post-fetch handoff wait can yield a batch at all — an idle server
      never pays the wait on its own completion path.
    """

    __slots__ = ("_event", "_lock", "_armed")

    def __init__(self):
        self._event = threading.Event()
        self._lock = make_lock("serve.engine.DispatchGate._lock")
        self._armed: set = set()

    def set_ready(self) -> None:
        self._event.set()

    def clear(self) -> None:
        self._event.clear()

    def is_ready(self) -> bool:
        return self._event.is_set()

    def arm(self, key) -> None:
        with self._lock:
            self._armed.add(key)

    def disarm(self, key) -> None:
        with self._lock:
            self._armed.discard(key)

    def armed(self) -> bool:
        with self._lock:
            return bool(self._armed)


class DeviceDispatcher:
    """The single device thread: bounded in-queue → one-behind dispatch.

    ``on_batch(assembled, detections_np)`` runs HERE, after batch N+1 has
    been dispatched (or immediately when the queue is idle) — conversion
    and future-fulfillment overlap device compute exactly as the eval
    driver's fetch-convert of batch N−1 overlaps batch N's NMS.
    ``on_fatal(exc)`` routes a crash to the frontend (shm error contract).
    With a ``gate`` (continuous mode) the loop additionally publishes
    device readiness so partial batches seal against it.
    """

    _POLL_S = 0.05

    def __init__(
        self,
        engine: DetectEngine,
        batch_queue: queue.Queue,
        on_batch: Callable[[AssembledBatch, object], None],
        on_fatal: Callable[[BaseException], None],
        stop: threading.Event,
        gate: DispatchGate | None = None,
    ):
        self._engine = engine
        self._queue = batch_queue
        self._on_batch = on_batch
        self._on_fatal = on_fatal
        self._stop = stop
        self._gate = gate
        self.dispatched_batches = 0
        # watchdog: registers in _run() at thread start.
        self.thread = threading.Thread(
            target=self._run, daemon=True, name="serve-dispatch"
        )
        self.thread.start()

    def _dispatch(self, assembled: AssembledBatch):
        with trace.span(
            "serve_dispatch",
            bucket=f"{assembled.hw[0]}x{assembled.hw[1]}",
            n=len(assembled.requests),
        ):
            det = self._engine.dispatch(assembled.hw, assembled.images)
        self.dispatched_batches += 1
        if trace.enabled():
            trace.counter("serve.dispatch_qsize", self._queue.qsize())
        return det

    def _fetch(self, pending):
        assembled, det = pending
        with trace.span(
            "serve_fetch", bucket=f"{assembled.hw[0]}x{assembled.hw[1]}"
        ):
            return self._engine.fetch(det)

    def _finish(self, pending) -> None:
        self._on_batch(pending[0], self._fetch(pending))

    # Post-fetch handoff: how long the dispatcher gives an ARMED batcher
    # to seal against the just-raised gate before converting anyway.
    # Covers the batcher's armed poll (~2 ms) with margin; only ever
    # paid when slots are actually claimed.
    _HANDOFF_S = 0.02

    def _idle_flush(self, pending):
        """Queue ran dry with one batch in flight: fetch it now (overlap
        never costs latency under light load).  Continuous mode raises
        the gate the moment the results land — the assembling batch
        (claiming slots this whole round) seals against it and is
        dispatched BEFORE the fetched batch's conversion, so the device
        hop N → N+1 never waits on host-side convert work.  Returns the
        new pending batch (or None)."""
        if self._gate is None:
            self._finish(pending)
            return None
        fetched = self._fetch(pending)
        self._gate.set_ready()
        nxt = None
        try:
            if self._gate.armed():
                # Claimed slots exist: give their batcher one beat to
                # seal N+1 so it rides now, not a poll later.
                nxt = self._queue.get(timeout=self._HANDOFF_S)
            else:
                nxt = self._queue.get_nowait()
        except queue.Empty:
            pass  # still idle: the gate stays set
        if nxt is not None:
            self._gate.clear()
            det = self._dispatch(nxt)
        self._on_batch(pending[0], fetched)
        return (nxt, det) if nxt is not None else None

    def _run(self) -> None:
        # Beats on every poll (an idle dispatcher is healthy); a wedged
        # device_get — the canonical dead-device-stream hang — stops the
        # heartbeat, which is exactly what the watchdog exists to name.
        hb = watchdog.register(
            "serve-dispatch",
            details=lambda: {
                "qsize": self._queue.qsize(),
                "dispatched": self.dispatched_batches,
            },
        )
        pending = None
        try:
            while True:
                hb.beat()
                if self._stop.is_set():
                    return
                if self._gate is not None and pending is None:
                    self._gate.set_ready()  # fully idle device
                try:
                    if self._gate is not None and pending is not None:
                        # Continuous: never park a finished device round
                        # behind the poll — no queued batch means go
                        # straight to the fetch (which blocks on device
                        # compute; the gate lets the next batch seal
                        # DURING it and ride at fetch-return).
                        assembled = self._queue.get_nowait()
                    else:
                        assembled = self._queue.get(timeout=self._POLL_S)
                except queue.Empty:
                    if pending is not None:
                        pending = self._idle_flush(pending)
                    continue
                if self._gate is not None:
                    self._gate.clear()
                det = self._dispatch(assembled)
                if pending is not None:
                    self._finish(pending)
                pending = (assembled, det)
        except BaseException as exc:
            self._on_fatal(exc)
        finally:
            # A pending batch at exit needs no flush: the clean close path
            # (frontend drain) waits for in-flight == 0 BEFORE setting
            # stop (the idle-flush above fetched it), and the abort/crash
            # paths reject every outstanding future at the frontend.
            hb.close()


__all__ = [
    "DetectEngine",
    "DeviceDispatcher",
    "DispatchGate",
    "IdentityLabelMap",
    "stop_gated_put",
]
