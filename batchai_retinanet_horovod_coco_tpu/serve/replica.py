"""Replica handles: one uniform surface over in-process and remote engines.

The fleet router (serve/fleet.py) speaks to every replica through this
interface — it never cares whether the ``DetectionServer`` lives in this
process (N engines across local devices) or behind the serve CLI's HTTP
frontend on another host:

- ``replica_id`` / ``version`` — stable identity (ISSUE 12 satellite:
  the router and canary gate attribute health and weight by it; the
  fields ride in every ``/healthz`` 200 payload's ``load`` block);
- ``healthz()`` — ``(status_code, payload)``; anything but 200 is a
  breaker signal.  Network failure is reported as code 0 (the poller
  treats it like a 503, it must never raise out of the poll loop);
- ``detect(payload, timeout_s)`` — one blocking request.  The error
  taxonomy is the serve frontend's (``RequestRejected`` with a reason,
  ``RequestTimeout``) plus ``ReplicaUnavailable`` for "this replica is
  dead/unreachable" — the one case the router may re-dispatch once.

``spawn_http_replica`` is the subprocess-per-host constructor: it forks
the existing serve CLI (``python -m …serve``) on a pinned port and waits
for its ``/healthz`` with the shared backoff policy — the chaos serve
leg and ``make fleet-smoke`` build their fleets with it.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import urllib.error
import urllib.request

from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    RequestRejected,
    RequestTimeout,
    ServeError,
    ServerClosed,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy


class ReplicaUnavailable(ServeError):
    """This replica cannot take the request (dead process, refused
    connection, crashed worker).  The error class that opens the
    breaker IMMEDIATELY on the request path and triggers re-dispatch.
    (A replica-level shed is also retried once on another replica —
    but it only trips the breaker after a consecutive run, and a
    timeout is a request outcome, never a replica death.)"""


class LocalReplica:
    """A ``DetectionServer`` in this process.

    ``healthz`` mirrors the HTTP frontend's verdict: the process-wide
    watchdog verdict (all in-process replicas share one process, hence
    one watchdog), 503 when this server has crashed or stopped
    accepting, and the server's ``load_fields()`` (replica_id, version,
    queue depths, p99) as the ``load`` block either way.
    """

    def __init__(self, server):
        self._server = server

    @property
    def server(self):
        return self._server

    @property
    def replica_id(self) -> str:
        return self._server.replica_id

    @property
    def version(self) -> str:
        return getattr(self._server.engine, "version", "live")

    def healthz(self) -> tuple[int, dict]:
        load = self._server.load_fields()
        if self._server._error is not None:
            return 503, {"status": "crashed", "load": load}
        if not load.get("accepting", False):
            return 503, {"status": "draining", "load": load}
        code, payload = telemetry.healthz()
        payload["load"] = load
        return code, payload

    def detect(
        self,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> list[dict]:
        try:
            fut = self._server.submit(
                payload, timeout_s=timeout_s, trace_id=trace_id
            )
            return fut.result(timeout=timeout_s)
        except (ServerClosed, ServerError) as exc:
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unavailable: {exc}"
            ) from exc
        except TimeoutError as exc:  # future wait expired
            raise RequestTimeout(str(exc)) from exc

    # ---- streaming sessions (ISSUE 18) -----------------------------------

    @property
    def stream_manager(self):
        """Lazily-attached ``StreamManager`` over this replica's server
        (one per replica; created on first streaming use so single-image
        fleets never pay the delivery thread)."""
        if getattr(self, "_stream", None) is None:
            from batchai_retinanet_horovod_coco_tpu.serve.stream import (
                StreamManager,
            )

            self._stream = StreamManager(self._server)
        return self._stream

    def stream_open(
        self,
        width: int | None = None,
        height: int | None = None,
        trace_id: str | None = None,
    ) -> dict:
        try:
            return self.stream_manager.open_stream(
                width=width, height=height, trace_id=trace_id
            )
        except (ServerClosed, ServerError) as exc:
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unavailable: {exc}"
            ) from exc

    def stream_frame(
        self,
        session_id: str,
        seq: int,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> tuple[list[dict], bool]:
        try:
            fut = self.stream_manager.submit_frame(
                session_id, seq, payload,
                timeout_s=timeout_s, trace_id=trace_id,
            )
            return fut.result(timeout=timeout_s), bool(fut.cache_hit)
        except (ServerClosed, ServerError) as exc:
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unavailable: {exc}"
            ) from exc
        except TimeoutError as exc:  # future wait expired
            raise RequestTimeout(str(exc)) from exc

    def stream_close(self, session_id: str) -> dict:
        try:
            return self.stream_manager.close_stream(session_id)
        except (ServerClosed, ServerError) as exc:
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unavailable: {exc}"
            ) from exc

    def metrics_text(self) -> str | None:
        """This replica's Prometheus exposition — the federation scrape
        surface (ISSUE 15; same payload the HTTP frontend's /metrics
        serves).  None = unscrapable this sweep, never raises.  A closed
        or crashed server reports None like a dead HTTP replica would:
        its registry object outlives it, and a frozen exposition must
        DROP from the federated view, not masquerade as live."""
        srv = self._server
        if srv._error is not None or getattr(srv, "_closed", False):
            return None
        try:
            return srv.telemetry.prometheus_text()
        except Exception:
            return None

    def drain(self, timeout_s: float = 5.0) -> None:
        """Stop accepting, let in-flight finish (bounded) — the canary
        rollback path.  Further submits shed with ``shutting_down``."""
        self._server.close(drain=True, timeout_s=timeout_s)

    def close(self) -> None:
        if getattr(self, "_stream", None) is not None:
            self._stream.close()
        self._server.close(drain=False)


class HttpReplica:
    """A replica behind the serve CLI's HTTP frontend (subprocess/host).

    Identity is learned from the first healthy ``/healthz`` payload
    (its ``load.replica_id`` / ``load.version`` fields) and kept stable
    afterwards; until then the constructor-provided fallbacks hold.
    """

    def __init__(
        self,
        base_url: str,
        replica_id: str | None = None,
        version: str = "unknown",
        timeout_s: float = 10.0,
        health_timeout_s: float = 2.5,
    ):
        self.base_url = base_url.rstrip("/")
        self._replica_id = replica_id or self.base_url
        self._version = version
        self._timeout_s = timeout_s
        # Health probes get a TIGHTER bound than requests: the fleet
        # poller sweeps replicas serially, so one black-holed host must
        # not starve the whole fleet's weight updates for timeout_s.
        self._health_timeout_s = min(health_timeout_s, timeout_s)

    @property
    def replica_id(self) -> str:
        return self._replica_id

    @property
    def version(self) -> str:
        return self._version

    def healthz(self) -> tuple[int, dict]:
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/healthz", timeout=self._health_timeout_s
            ) as r:
                payload = json.loads(r.read().decode())
                code = r.status
        except urllib.error.HTTPError as e:  # 503 is data, not an error
            try:
                payload = json.loads(e.read().decode())
            except Exception:
                payload = {}
            code = e.code
        except Exception as e:  # refused/reset/timeout — poller signal
            return 0, {"status": "unreachable", "error": repr(e)}
        load = payload.get("load") or {}
        if code == 200 and load.get("replica_id"):
            self._replica_id = str(load["replica_id"])
            self._version = str(load.get("version") or self._version)
        return code, payload

    def detect(
        self,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> list[dict]:
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise RequestRejected(
                "decode_error", "HTTP replicas take encoded image bytes"
            )
        req = urllib.request.Request(
            f"{self.base_url}/detect", data=bytes(payload), method="POST"
        )
        if trace_id is not None:
            # The cross-process span-context hop (ISSUE 15): the replica
            # frontend parents its serve_request span under this id.
            req.add_header(trace.TRACE_HEADER, trace_id)
        timeout = self._timeout_s if timeout_s is None else timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode())["detections"]
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read().decode())
            except Exception:
                pass
            if e.code in (400, 503):
                raise RequestRejected(
                    str(body.get("reason", "rejected"))
                ) from e
            if e.code == 504:
                raise RequestTimeout("replica deadline exceeded") from e
            raise ReplicaUnavailable(
                f"replica {self.replica_id} HTTP {e.code}"
            ) from e
        except Exception as e:
            # A socket timeout is a SLOW replica, not a dead one: the
            # request ran out of time (a request outcome — never a
            # breaker hit, never re-dispatched while the original may
            # still be executing).  Refused/reset = actually dead.
            if isinstance(e, TimeoutError) or isinstance(
                getattr(e, "reason", None), TimeoutError
            ):
                raise RequestTimeout(
                    f"replica {self.replica_id} timed out"
                ) from e
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unreachable: {e!r}"
            ) from e

    # ---- streaming sessions (ISSUE 18) -----------------------------------

    def _stream_request(
        self,
        path: str,
        data: bytes,
        headers: dict,
        timeout_s: float | None,
        trace_id: str | None,
    ) -> dict:
        """POST one /stream/* call with detect()'s exact error mapping
        plus the 404 flavor (unknown session → ``unknown_stream``, a
        re-open signal, never a breaker hit)."""
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method="POST"
        )
        for k, v in headers.items():
            req.add_header(k, v)
        if trace_id is not None:
            req.add_header(trace.TRACE_HEADER, trace_id)
        timeout = self._timeout_s if timeout_s is None else timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read().decode())
            except Exception:
                pass
            if e.code in (400, 404, 503):
                raise RequestRejected(
                    str(body.get("reason", "rejected"))
                ) from e
            if e.code == 504:
                raise RequestTimeout("replica deadline exceeded") from e
            raise ReplicaUnavailable(
                f"replica {self.replica_id} HTTP {e.code}"
            ) from e
        except Exception as e:
            if isinstance(e, TimeoutError) or isinstance(
                getattr(e, "reason", None), TimeoutError
            ):
                raise RequestTimeout(
                    f"replica {self.replica_id} timed out"
                ) from e
            raise ReplicaUnavailable(
                f"replica {self.replica_id} unreachable: {e!r}"
            ) from e

    def stream_open(
        self,
        width: int | None = None,
        height: int | None = None,
        trace_id: str | None = None,
    ) -> dict:
        spec = {}
        if width:
            spec["width"] = int(width)
        if height:
            spec["height"] = int(height)
        return self._stream_request(
            "/stream/open", json.dumps(spec).encode(), {},
            None, trace_id,
        )

    def stream_frame(
        self,
        session_id: str,
        seq: int,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> tuple[list[dict], bool]:
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise RequestRejected(
                "decode_error", "HTTP replicas take encoded frame bytes"
            )
        headers = {
            "X-Retinanet-Stream": session_id,
            "X-Retinanet-Frame": str(int(seq)),
        }
        if timeout_s is not None:
            headers["X-Retinanet-Deadline-Ms"] = str(timeout_s * 1e3)
        out = self._stream_request(
            "/stream/frame", bytes(payload), headers, timeout_s, trace_id
        )
        return out["detections"], bool(out.get("cache_hit", False))

    def stream_close(self, session_id: str) -> dict:
        out = self._stream_request(
            "/stream/close", b"",
            {"X-Retinanet-Stream": session_id}, None, None,
        )
        return out.get("stats", {})

    def metrics_text(self) -> str | None:
        """GET /metrics — the federation scrape surface (ISSUE 15).
        Health-probe timeout bound (the scrape sweep is serial, like the
        health poll); None on any failure, never raises."""
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/metrics", timeout=self._health_timeout_s
            ) as r:
                return r.read().decode()
        except Exception:
            return None

    def drain(self, timeout_s: float = 5.0) -> None:
        # No remote admin surface: "drain" for an HTTP replica is the
        # router holding its weight at zero while in-flight work on the
        # replica finishes under the frontend's own drain contract.
        pass

    def close(self) -> None:
        pass


class RespawnBudget:
    """Budgeted respawn supervision for ONE replica slot (ISSUE 19).

    The fleet CLI used to respawn a dead replica unconditionally every
    supervision tick — a replica that dies instantly on spawn (bad
    flag, poisoned export, port conflict) was respawned in a tight
    loop forever.  This object bounds that: each death schedules the
    next respawn on the policy's deterministic-jitter backoff schedule
    (``delay_s(deaths-1)``), and once deaths exceed ``max_tries``
    without an intervening recovery the slot is EXHAUSTED — the
    supervisor emits ``respawn_budget_exhausted`` exactly once and
    leaves the slot to the autoscaler.  A replica that stays alive
    ``reset_after_s`` past its last death earns a fresh budget (rare
    crashes over a long run must not accumulate into an exhaustion).

    Pure state machine on an injectable clock — no sleeping, no
    threads; the supervision loop drives it.
    """

    def __init__(self, policy: BackoffPolicy, reset_after_s: float = 60.0):
        self.policy = policy
        self.reset_after_s = reset_after_s
        self.deaths = 0
        self.exhausted = False
        self.next_respawn_t = 0.0
        self._last_death_t: float | None = None

    def note_alive(self, now: float) -> None:
        """The replica is up: reset the budget once it has survived
        ``reset_after_s`` past the last death."""
        if (
            self.deaths
            and not self.exhausted
            and self._last_death_t is not None
            and now - self._last_death_t >= self.reset_after_s
        ):
            self.deaths = 0
            self._last_death_t = None

    def note_death(self, now: float) -> bool:
        """Record one death.  Returns True when a respawn is still in
        budget (``next_respawn_t`` holds when); False = exhausted."""
        if (
            self.deaths
            and self._last_death_t is not None
            and now - self._last_death_t >= self.reset_after_s
        ):
            self.deaths = 0  # long-lived replica: fresh budget
        self._last_death_t = now
        self.deaths += 1
        if self.deaths > self.policy.max_tries:
            self.exhausted = True
            return False
        self.next_respawn_t = now + self.policy.delay_s(self.deaths - 1)
        return True

    def ready(self, now: float) -> bool:
        return not self.exhausted and now >= self.next_respawn_t


def release_subprocess(
    proc: subprocess.Popen,
    sigterm_timeout_s: float = 10.0,
) -> int | None:
    """Drain-aware subprocess release (ISSUE 19): SIGTERM (the serve
    CLI maps it to its bounded in-flight drain), bounded wait, SIGKILL
    only if the drain never finishes.  Returns the exit code (None if
    even the kill-wait expired — the caller should not block forever)."""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=sigterm_timeout_s)
        except Exception:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except Exception:
                return None
    return proc.returncode


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe).  Small race window
    between close and the child's bind — acceptable for smoke harnesses,
    which retry the spawn on a failed health wait."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def spawn_http_replica(
    replica_id: str,
    port: int | None = None,
    host: str = "127.0.0.1",
    export_dir: str | None = None,
    stub_delay_ms: float | None = None,
    extra_args: list[str] | None = None,
    wait_policy: BackoffPolicy = BackoffPolicy(
        max_tries=120, base_s=0.5, multiplier=1.0, jitter=0.0
    ),
    env: dict | None = None,
) -> tuple[subprocess.Popen, "HttpReplica"]:
    """Fork one serve-CLI replica on a pinned port and wait for health.

    ``export_dir=None`` spawns a ``--stub-engine`` replica (the fleet
    smoke / chaos legs); the pinned port is what lets a breaker-open
    replica be RESTARTED in place and readmitted by the half-open probe.
    Returns ``(process, HttpReplica)``; the caller owns the process.
    """
    port = free_port(host) if port is None else port
    cmd = [
        sys.executable, "-m", "batchai_retinanet_horovod_coco_tpu.serve",
        "--http", str(port), "--host", host, "--replica-id", replica_id,
    ]
    if export_dir is not None:
        cmd += ["--export-dir", export_dir]
    else:
        cmd += ["--stub-engine"]
        if stub_delay_ms is not None:
            cmd += ["--stub-delay-ms", str(stub_delay_ms)]
    cmd += extra_args or []
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # The repo is path-based (not pip-installed): make sure the child
    # resolves the package no matter the caller's cwd.
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    child_env["PYTHONPATH"] = (
        repo_root + os.pathsep + child_env["PYTHONPATH"]
        if child_env.get("PYTHONPATH") else repo_root
    )
    child_env.update(env or {})
    proc = subprocess.Popen(
        cmd, env=child_env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    replica = HttpReplica(f"http://{host}:{port}", replica_id=replica_id)

    def probe():
        if proc.poll() is not None:
            return f"replica process exited rc={proc.returncode}"
        code, _payload = replica.healthz()
        return None if code == 200 else f"healthz {code}"

    _attempts, err = wait_policy.retry(probe)
    if err is not None:
        proc.kill()
        raise ReplicaUnavailable(
            f"spawned replica {replica_id} never became healthy: {err}"
        )
    return proc, replica


__all__ = [
    "HttpReplica",
    "LocalReplica",
    "ReplicaUnavailable",
    "RespawnBudget",
    "free_port",
    "release_subprocess",
    "spawn_http_replica",
]
