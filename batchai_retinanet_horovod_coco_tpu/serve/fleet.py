"""Fault-tolerant serve fleet: replicated engines behind a health-weighted
router with circuit breaking, fleet admission control, and an SLO-gated
canary rollout (ISSUE 12 — the serve-side sibling of the elastic-training
PR).

``FleetRouter`` consumes exactly the read surface PR 8 shaped "for a
future fleet router": each replica's ``/healthz`` 200 payload carries
``load_fields()`` (replica_id, version, inflight, queue depth vs bound,
windowed p99, accepting), and the router turns those into routing
weights — continuously, via a health poller on an injectable clock, so
the whole state machine is testable without sleeping
(tests/unit/test_fleet.py).

The machines:

- **Weighting** (``replica_weight``): queue headroom x inflight damping
  x relative p99 — a loaded or slow replica takes proportionally less
  traffic *before* it gets sick enough to shed.
- **Circuit breaker** (per replica): CLOSED → OPEN on a health-poll
  failure/503 or a dead-replica request failure (and on
  ``shed_trip`` *consecutive* request sheds — one 503 under load is
  shedding working-as-designed, a run of them is a sick replica);
  OPEN → HALF_OPEN when the breaker's bounded backoff (+ deterministic
  per-replica jitter, utils/backoff.py) elapses; the next health poll IS
  the half-open probe — success readmits (CLOSED, weight restored),
  failure re-opens with the next backoff step.
- **Re-dispatch**: a request in flight on a replica that dies under it
  (``ReplicaUnavailable``) is re-dispatched to another replica AT MOST
  ``redispatch_limit`` (default once), and only if its deadline allows.
  A replica-level shed (503) is retried on another replica under the
  SAME bounded budget (a racing shed must not fail a request the rest
  of the fleet had headroom for); timeouts and ``decode_error`` are
  request outcomes, never retried.
- **Fleet admission control**: over ``max_inflight`` (default: the sum
  of the replicas' advertised admission capacities) the fleet sheds at
  the edge with ``fleet_overloaded``; with no routable replica it sheds
  ``no_replica_available`` — overload never queues into a sick replica.
- **Canary gate** (``add_canary``): a replica from a different export
  ``version`` takes ``canary_weight``-scaled traffic while a DEDICATED
  ``SloMonitor`` (obs/slo.py — the anti-flap/once-per-sustained-breach
  machinery, on the same injectable clock) watches its p99 ratio vs the
  fleet baseline and its shed rate.  A sustained breach drains it and
  rolls the fleet back to baseline weights with exactly ONE structured
  ``canary_rollback`` event — measured feedback drives the rollout, no
  human in the loop (the TVM lesson, applied to deployment).

Everything observable lands in ``router.telemetry`` (fleet latency
summary, per-replica weight/breaker gauges, shed/redispatch/rollback
counters) — scraped by ``GET /metrics`` on the fleet frontend
(``serve_fleet_http``) exactly like a single replica's.

Fleet-scope observability (ISSUE 15) — the read side learns there is
more than one process:

- **Distributed request tracing**: the fleet frontend mints one trace id
  per request (``X-Retinanet-Trace``), wraps routing in a
  ``fleet_request`` span carrying it, propagates it through the replica
  handles to each replica frontend (whose ``serve_request`` span parents
  under it), and echoes it on every response — so one slow request is
  followable edge → router → replica slot → device → response in the
  merged Perfetto trace, re-dispatches landing on the second replica's
  track under the SAME id.
- **Metrics federation**: a dedicated watchdog-registered scrape thread
  pulls each replica's ``/metrics`` on the health-poll cadence
  (``metrics_text()`` on the replica handles) and re-exposes every
  series replica-labeled on the fleet registry, next to derived fleet
  aggregates (``fleet_availability``, ``fleet_federated_p99_ms``,
  ``fleet_federated_shed_total``) — one ``snapshot()`` the SLO monitor
  evaluates fleet-level rules on (``obs.slo.fleet_availability_rule``).
- **Event completeness**: every fleet state transition — breaker
  open/half-open/readmit, re-dispatch, canary start/promote/rollback,
  replica spawn/death/respawn — emits BOTH a structured sink event and a
  ``trace.instant`` carrying the replica id, so fleet decisions sit on
  the Perfetto timeline next to the request spans they explain.

All of it is read-only: federation and tracing observe — they never
alter routing weights, batching, or any per-request result (PARITY.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import sys
import threading
import uuid
import zlib
from typing import Any

from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
    Registry,
    parse_exposition_samples,
)
from batchai_retinanet_horovod_coco_tpu.obs.events import emit_event
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    LatencyStats,
    RequestRejected,
    RequestTimeout,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.serve.replica import (
    ReplicaUnavailable,
)
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock

# Breaker states (also the fleet_breaker_state gauge encoding).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
DRAINED = "drained"  # canary rolled back / replica administratively out
_STATE_CODE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0, DRAINED: 3.0}


def replica_weight(load: dict | None, p99_ref: float | None = None) -> float:
    """One replica's routing weight from its advertised load fields.

    ``headroom / (1 + inflight/capacity)``, scaled down by
    ``p99_ref / p99`` when this replica's windowed p99 is worse than the
    fleet's best (``p99_ref``), and by the slot-occupancy factor
    ``(1 + free_slots/slot_capacity) / 2`` when the replica advertises
    its assembling-batch slots (ISSUE 14): a replica whose device slots
    are fully claimed takes half the traffic of one with an idle pool,
    so the fleet steers load AT idle device capacity before queues ever
    grow.  Replicas that don't advertise slots (older builds) get the
    neutral factor 1 — the deterministic tie-break is the formula
    itself: identical load fields always produce identical weights, and
    the router's candidate order is fixed by replica_id.  0 means
    unroutable: not accepting, or no admission headroom left (the edge
    sheds instead of queueing).  Pure — pinned exactly by
    tests/unit/test_fleet.py.
    """
    if not load or not load.get("accepting", False):
        return 0.0
    cap = max(1, int(load.get("admission_capacity") or 1))
    qsize = max(0, int(load.get("admission_qsize") or 0))
    headroom = max(0.0, 1.0 - qsize / cap)
    inflight = max(0, int(load.get("inflight") or 0))
    w = headroom / (1.0 + inflight / cap)
    slot_cap = load.get("slot_capacity")
    if slot_cap and int(slot_cap) > 0:
        free = min(
            max(0, int(load.get("free_slots") or 0)), int(slot_cap)
        )
        w *= (1.0 + free / int(slot_cap)) / 2.0
    p99 = load.get("p99_ms")
    if p99 and p99_ref and float(p99) > 0 and float(p99_ref) > 0:
        w *= min(1.0, float(p99_ref) / float(p99))
    return round(w, 6)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router knobs.  The breaker backoff is a shared ``BackoffPolicy``;
    each replica derives a deterministic per-replica jitter seed from its
    id, so probe schedules are reproducible but decorrelated."""

    poll_interval_s: float = 1.0
    # Health-poll failures/503s before a CLOSED breaker opens (1 = the
    # first failed poll opens it — a poll failure is already a timeout's
    # worth of evidence).
    fail_threshold: int = 1
    # CONSECUTIVE request-level sheds before the breaker opens (sheds are
    # load signals first; a run of them is a sick replica).
    shed_trip: int = 3
    # Half-open probe cadence after the breaker opens.
    probe_backoff: BackoffPolicy = BackoffPolicy(
        max_tries=1_000_000, base_s=0.5, multiplier=2.0, ceiling_s=10.0,
        jitter=0.2,
    )
    # Fleet admission bound; None = sum of advertised admission
    # capacities of routable replicas (re-derived as replicas come/go).
    max_inflight: int | None = None
    # Default per-request deadline at the fleet edge.
    default_timeout_s: float | None = 30.0
    # A dead replica's in-flight requests are re-dispatched at most this
    # many times (deadline allowing).
    redispatch_limit: int = 1
    # Canary weight fraction while under SLO evaluation.
    canary_weight: float = 0.25
    # Canary gate rules: p99 ratio vs fleet baseline + shed-per-poll.
    canary_p99_factor: float = 1.5
    canary_shed_per_poll: float = 0.0
    canary_for_s: float = 5.0
    canary_poll_s: float = 1.0
    # Canary drain budget on rollback (LocalReplica close bound).
    canary_drain_timeout_s: float = 5.0
    latency_window: int = 4096
    seed: int = 0
    # Streaming affinity (ISSUE 18): a fleet-edge stream pin with no
    # frame activity for this long is dropped by the health-poll sweep
    # (the replica-side session reaps itself independently; a client
    # returning after a reap gets ``unknown_stream`` and re-opens).
    stream_idle_timeout_s: float = 60.0


# Backend rejection reasons raised BEFORE the replica's StreamManager
# consumes the frame's sequence number (its admission checks).  Anything
# else that surfaces after admission — decode_error, a queue-full shed
# landing on the frame's future, a deadline expiry — has already
# advanced the backend's expected seq, and the edge must advance with
# it or every later frame on the stream sheds ``stream_out_of_order``.
_STREAM_PRE_ADMISSION = frozenset({
    "unknown_stream", "stream_out_of_order", "stream_backlogged",
    "stream_limit", "shutting_down", "no_replica_available",
})


class _StreamPin:
    """One client stream's fleet-edge affinity record (ISSUE 18): the
    client-facing session id maps to a pinned replica plus the BACKEND
    session living on it.  ``lock`` serializes this stream's frames
    through the edge — monotonic ordering and re-pin atomicity come from
    the same mutex (concurrency across streams is untouched; one stream's
    frames are inherently sequential anyway)."""

    __slots__ = (
        "sid", "lock", "st", "backend_sid", "backend_seq", "next_seq",
        "width", "height", "trace_id", "last_active", "repins",
    )

    def __init__(self, sid, st, backend_sid, width, height, trace_id,
                 now: float):
        self.sid = sid
        self.lock = make_lock("serve.fleet._StreamPin.lock")
        self.st = st  # the pinned _ReplicaState
        self.backend_sid = backend_sid
        self.backend_seq = 0  # the PINNED replica's expected seq
        self.next_seq = 0  # the CLIENT-facing expected seq
        self.width = width
        self.height = height
        self.trace_id = trace_id
        self.last_active = now
        self.repins = 0


class _ReplicaState:
    __slots__ = (
        "replica", "state", "weight", "load", "poll_failures",
        "shed_strikes", "open_count", "next_probe_t", "is_canary",
    )

    def __init__(self, replica, is_canary: bool = False):
        self.replica = replica
        self.state = CLOSED
        self.weight = 0.0
        self.load: dict = {}
        self.poll_failures = 0
        self.shed_strikes = 0
        self.open_count = 0  # backoff step for the half-open probe
        self.next_probe_t = 0.0
        self.is_canary = is_canary


class FleetRouter:
    """N replicas behind one weighted, breaker-guarded ``detect()``.

    ``detect()`` is blocking and thread-safe (the fleet HTTP frontend
    calls it from per-request handler threads); ``poll_once(now=...)``
    advances the health/breaker state machine on an injectable clock —
    ``start_polling()`` runs it on a watchdog-registered thread in
    production, tests drive it directly.
    """

    def __init__(
        self,
        replicas: list,
        config: FleetConfig = FleetConfig(),
        sink: Any = None,
        auto_poll: bool = True,
        initial_poll: bool = True,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.config = config
        self.sink = sink
        self.stats = LatencyStats(window=config.latency_window)
        self._states = [_ReplicaState(r) for r in replicas]
        self._lock = make_lock("serve.fleet.FleetRouter._lock")
        self._rng = random.Random(config.seed)
        self._accepting = True
        self._inflight = 0
        self._error: BaseException | None = None
        self._redispatches = 0
        self._breaker_opens = 0
        self._rollbacks = 0
        # Streaming affinity (ISSUE 18): client session id → pin.
        self._streams: dict[str, _StreamPin] = {}
        self._stream_repins = 0
        # Canary machinery (armed by add_canary).
        self._canary: _ReplicaState | None = None
        self._canary_monitor = None
        self._canary_outcome: str | None = None  # None|rolled_back|promoted

        # Metrics federation (ISSUE 15): replica_id → (types, samples)
        # from the last successful scrape of that replica's /metrics;
        # re-exposed replica-labeled by _federation_samples.
        self._federated: dict[str, tuple[dict, list]] = {}
        self._fed_error: BaseException | None = None

        self.telemetry = Registry()
        self.telemetry.histogram(
            "fleet_request_latency_ms",
            "fleet-edge request latency over the recent window",
            source=self.stats.window_ms,
        )
        self.telemetry.register_collector(self._telemetry_samples)
        self.telemetry.register_collector(self._federation_samples)
        # The fleet process's own health (poller / scrape / supervisor
        # heartbeats) on the same scrape surface, so the built-in stall
        # SLO rule works at the fleet edge too.
        self.telemetry.register_collector(telemetry.watchdog_collector())

        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fed_thread: threading.Thread | None = None
        if initial_poll:
            self.poll_once()
        if auto_poll:
            self.start_polling()

    # ---- identity helpers ------------------------------------------------

    def _backoff_for(self, st: _ReplicaState) -> BackoffPolicy:
        """The shared probe policy, re-seeded per replica id so probe
        jitter is deterministic yet decorrelated across the fleet."""
        seed = zlib.crc32(str(st.replica.replica_id).encode())
        return dataclasses.replace(
            self.config.probe_backoff, seed=self.config.seed ^ seed
        )

    # ---- health poll + breaker state machine -----------------------------

    def poll_once(self, now: float | None = None) -> None:
        """One health sweep: poll every replica that is due, apply the
        breaker transitions, recompute weights.  Injectable ``now``."""
        now = monotonic_s() if now is None else now
        for st in list(self._states):
            probing = False
            with self._lock:
                if st.state == DRAINED:
                    continue
                if st.state == OPEN:
                    if now < st.next_probe_t:
                        continue  # still backing off
                    st.state = HALF_OPEN  # this poll IS the probe
                    probing = True
            if probing:
                # Half-open is a fleet decision too (ISSUE 15): on the
                # timeline it explains the probe traffic that follows.
                self._emit_event(
                    "fleet_breaker_half_open",
                    replica_id=st.replica.replica_id,
                )
            try:
                code, payload = st.replica.healthz()
            except Exception as exc:  # a poller can never crash on a replica
                code, payload = 0, {"status": "poll_error", "error": repr(exc)}
            self._apply_poll(st, code, payload, now)
        self._recompute_weights()
        self._reap_stale_pins(now)

    def _apply_poll(
        self, st: _ReplicaState, code: int, payload: dict, now: float
    ) -> None:
        with self._lock:
            if code == 200:
                st.load = dict(payload.get("load") or {})
                st.poll_failures = 0
                st.shed_strikes = 0
                if st.state in (OPEN, HALF_OPEN):
                    st.state = CLOSED
                    st.open_count = 0
                    self._emit_event(
                        "fleet_breaker_close",
                        replica_id=st.replica.replica_id,
                    )
                return
            # Unhealthy poll (503 / unreachable / crashed).
            st.poll_failures += 1
            if st.state == CLOSED:
                if st.poll_failures >= self.config.fail_threshold:
                    self._open_locked(
                        st, now,
                        reason=str(payload.get("status") or f"healthz_{code}"),
                    )
            elif st.state == HALF_OPEN:
                # Probe failed: back to OPEN with the next backoff step.
                self._open_locked(st, now, reason="half_open_probe_failed")

    def _open_locked(
        self, st: _ReplicaState, now: float, reason: str
    ) -> None:
        """Transition to OPEN and schedule the half-open probe (caller
        holds the lock).  EVERY open — including a failed half-open
        probe re-opening — emits the event pair (ISSUE 15: no silent
        fleet transitions)."""
        st.state = OPEN
        st.weight = 0.0
        delay = self._backoff_for(st).delay_s(st.open_count)
        st.open_count += 1
        st.next_probe_t = now + delay
        self._breaker_opens += 1
        self._emit_event(
            "fleet_breaker_open",
            replica_id=st.replica.replica_id,
            reason=reason,
            probe_in_s=round(delay, 3),
        )

    def _note_request_failure(self, st: _ReplicaState) -> None:
        """A request found this replica dead (``ReplicaUnavailable``):
        open the breaker immediately — don't wait for the next poll."""
        with self._lock:
            if st.state in (CLOSED, HALF_OPEN):
                self._open_locked(st, monotonic_s(), reason="request_failed")

    def _note_request_shed(self, st: _ReplicaState) -> None:
        """A request-level 503: a load signal first, a breaker signal
        after ``shed_trip`` CONSECUTIVE ones."""
        with self._lock:
            st.shed_strikes += 1
            if st.state == CLOSED and st.shed_strikes >= self.config.shed_trip:
                self._open_locked(
                    st, monotonic_s(), reason="consecutive_sheds"
                )

    def _recompute_weights(self) -> None:
        with self._lock:
            routable = [
                st for st in self._states
                if st.state == CLOSED and st.load.get("accepting", False)
            ]
            p99s = [
                float(st.load["p99_ms"]) for st in routable
                if st.load.get("p99_ms")
            ]
            p99_ref = min(p99s) if p99s else None
            for st in self._states:
                if st.state != CLOSED:
                    st.weight = 0.0
                    continue
                w = replica_weight(st.load, p99_ref)
                if st.is_canary and self._canary_outcome is None:
                    w *= self.config.canary_weight
                st.weight = w

    # ---- routing ---------------------------------------------------------

    def _pick(self, exclude: set[int]) -> _ReplicaState | None:
        with self._lock:
            candidates = [
                st for st in self._states
                if st.state == CLOSED and st.weight > 0.0
                and id(st) not in exclude
            ]
            if not candidates:
                return None
            # Deterministic tie-break (ISSUE 14): the weighted draw walks
            # candidates in replica_id order, never registration/arrival
            # order, so equal weights resolve identically across runs
            # given the seeded RNG.
            candidates.sort(key=lambda st: str(st.replica.replica_id))
            total = sum(st.weight for st in candidates)
            x = self._rng.random() * total
            for st in candidates:
                x -= st.weight
                if x <= 0.0:
                    return st
            return candidates[-1]

    def _fleet_capacity(self) -> int:
        if self.config.max_inflight is not None:
            return self.config.max_inflight
        with self._lock:
            caps = [
                int(st.load.get("admission_capacity") or 0)
                for st in self._states
                if st.state == CLOSED
            ]
        return max(1, sum(caps))

    def detect(
        self,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> list[dict]:
        """Route one request; blocking.  Raises the serve taxonomy:
        ``RequestRejected(reason)`` on any shed (fleet edge or replica),
        ``RequestTimeout`` past the deadline, ``ServerError`` when every
        eligible replica failed underneath it.

        ``trace_id`` is the fleet-wide span context (ISSUE 15): minted
        here when tracing is on and none was supplied, wrapped in a
        ``fleet_request`` span on the edge track, and propagated to the
        replica handles so each attempt's ``serve_request`` span parents
        under the SAME id — a re-dispatched request's spans land on both
        replicas' tracks, linked by one Perfetto flow."""
        self._raise_pending()
        t0 = monotonic_s()
        if trace_id is None and trace.enabled():
            trace_id = trace.new_trace_id()
        if timeout_s is None:
            timeout_s = self.config.default_timeout_s
        deadline = None if timeout_s is None else t0 + timeout_s
        with self._lock:
            accepting = self._accepting
        if not accepting:
            self.stats.record_shed("shutting_down")
            raise RequestRejected("shutting_down")
        cap = self._fleet_capacity()
        with self._lock:
            if self._inflight >= cap:
                over = True
            else:
                over = False
                self._inflight += 1
        if over:
            self.stats.record_shed("fleet_overloaded")
            raise RequestRejected(
                "fleet_overloaded", f"fleet inflight at capacity {cap}"
            )
        span = (
            trace.begin("fleet_request", trace=trace_id)
            if trace_id is not None
            else None
        )
        if trace_id is not None:
            trace.flow_start("request", trace_id)
        try:
            return self._dispatch(payload, deadline, t0, trace_id)
        finally:
            # Terminate the flow on EVERY exit — failed and re-dispatched
            # requests are exactly the ones a post-mortem follows across
            # tracks, so their arrow chain must close too.
            if trace_id is not None:
                trace.flow_end("request", trace_id)
            trace.end(span)
            with self._lock:
                self._inflight -= 1

    def _dispatch(
        self, payload, deadline, t0: float, trace_id: str | None = None
    ) -> list[dict]:
        tried: set[int] = set()
        last_exc: BaseException | None = None
        attempts = self.config.redispatch_limit + 1
        for attempt in range(attempts):
            now = monotonic_s()
            if deadline is not None and now >= deadline:
                self.stats.record_timeout()
                raise RequestTimeout(
                    "fleet deadline expired before dispatch"
                ) from last_exc
            st = self._pick(tried)
            if st is None:
                if last_exc is None:
                    self.stats.record_shed("no_replica_available")
                    raise RequestRejected(
                        "no_replica_available",
                        "no routable replica (breakers open or zero headroom)",
                    )
                break  # a failure with no alternate left — classify below
            tried.add(id(st))
            if attempt > 0:
                with self._lock:
                    self._redispatches += 1
                # Sink event + trace instant (ISSUE 15): the re-dispatch
                # carries the trace id, so the hop from replica A's shed/
                # death to replica B's span is explicit on the timeline.
                self._emit_event(
                    "fleet_redispatch",
                    replica_id=st.replica.replica_id,
                    attempt=attempt,
                    **({"trace": trace_id} if trace_id else {}),
                )
            remaining = None if deadline is None else deadline - now
            try:
                if trace_id is None:
                    dets = st.replica.detect(payload, timeout_s=remaining)
                else:
                    dets = st.replica.detect(
                        payload, timeout_s=remaining, trace_id=trace_id
                    )
            except ReplicaUnavailable as exc:
                self._note_request_failure(st)
                self._recompute_weights()
                last_exc = exc
                continue  # deadline-checked at the top of the loop
            except RequestRejected as exc:
                if exc.reason == "decode_error":
                    # The client's fault — never a breaker/redispatch signal.
                    self.stats.record_shed(exc.reason)
                    raise
                self._note_request_shed(st)
                last_exc = exc
                continue
            except RequestTimeout:
                self.stats.record_timeout()
                raise
            with self._lock:
                st.shed_strikes = 0
            self.stats.record(monotonic_s() - t0)
            return dets
        # Exhausted: classify by the last replica-side outcome.
        if isinstance(last_exc, RequestRejected):
            self.stats.record_shed(last_exc.reason)
            raise last_exc
        self.stats.record_failure()
        err = ServerError(
            "every eligible replica failed this request "
            f"(redispatch limit {self.config.redispatch_limit})"
        )
        err.__cause__ = last_exc
        raise err

    def _raise_pending(self) -> None:
        if self._error is not None:
            raise ServerError("fleet health poller crashed") from self._error

    # ---- streaming session affinity (ISSUE 18) ---------------------------

    def stream_open(
        self,
        width: int | None = None,
        height: int | None = None,
        trace_id: str | None = None,
    ) -> dict:
        """Open a client stream: pick a replica with the weighted draw,
        open a BACKEND session on it, and pin the stream there — every
        subsequent frame routes to the pin (the delta cache and track
        stitcher are per-replica state; affinity is what makes them
        work).  The client-facing session id is minted HERE, decoupled
        from the backend id, so a re-pin is invisible to the client."""
        self._raise_pending()
        with self._lock:
            accepting = self._accepting
        if not accepting:
            self.stats.record_shed("shutting_down")
            raise RequestRejected("shutting_down")
        if trace_id is None and trace.enabled():
            trace_id = trace.new_trace_id()
        tried: set[int] = set()
        last_exc: BaseException | None = None
        for _ in range(self.config.redispatch_limit + 1):
            st = self._pick(tried)
            if st is None:
                break
            tried.add(id(st))
            try:
                out = st.replica.stream_open(
                    width=width, height=height, trace_id=trace_id
                )
            except ReplicaUnavailable as exc:
                self._note_request_failure(st)
                self._recompute_weights()
                last_exc = exc
                continue
            except RequestRejected as exc:
                # A per-replica session-table limit: try elsewhere.
                self._note_request_shed(st)
                last_exc = exc
                continue
            sid = uuid.uuid4().hex[:12]
            pin = _StreamPin(
                sid, st, out["session"], width, height, trace_id,
                monotonic_s(),
            )
            with self._lock:
                self._streams[sid] = pin
            return {
                "session": sid,
                "bucket": out.get("bucket"),
                "replica_id": st.replica.replica_id,
            }
        if isinstance(last_exc, RequestRejected):
            self.stats.record_shed(last_exc.reason)
            raise last_exc
        self.stats.record_shed("no_replica_available")
        raise RequestRejected(
            "no_replica_available", "no routable replica for stream open"
        ) from last_exc

    def stream_frame(
        self,
        session_id: str,
        seq: int,
        payload,
        timeout_s: float | None = None,
        trace_id: str | None = None,
    ) -> tuple[list[dict], bool]:
        """Route one frame to the stream's pinned replica; returns
        ``(detections, cache_hit)``.  On replica death the frame is NOT
        dropped: the breaker path re-pins the stream to another replica
        (one structured ``stream_repinned`` event) and retries the frame
        there — the new backend session starts a fresh track/cache
        history, which is the documented continuity cost of a kill."""
        self._raise_pending()
        with self._lock:
            pin = self._streams.get(session_id)
        if pin is None:
            self.stats.record_shed("unknown_stream")
            raise RequestRejected("unknown_stream", session_id)
        if timeout_s is None:
            timeout_s = self.config.default_timeout_s
        with pin.lock:
            pin.last_active = monotonic_s()
            if seq != pin.next_seq:
                raise RequestRejected(
                    "stream_out_of_order",
                    f"got seq {seq}, expected {pin.next_seq}",
                )
            pin.next_seq += 1
            return self._stream_dispatch(
                pin, seq, payload, timeout_s, trace_id or pin.trace_id
            )

    def _stream_dispatch(
        self, pin: _StreamPin, seq: int, payload, timeout_s, trace_id
    ) -> tuple[list[dict], bool]:
        """Under ``pin.lock``: one frame's pin → (maybe re-pin) → retry
        arc.  Bounded attempts; client-fault rejections propagate
        immediately (never a re-pin signal)."""
        last_exc: BaseException | None = None
        reopened = False
        for _attempt in range(3):
            st = pin.st
            with self._lock:
                routable = st.state == CLOSED and st.weight > 0.0
            if routable:
                try:
                    dets, hit = st.replica.stream_frame(
                        pin.backend_sid, pin.backend_seq, payload,
                        timeout_s=timeout_s, trace_id=trace_id,
                    )
                except ReplicaUnavailable as exc:
                    last_exc = exc
                    self._note_request_failure(st)
                    self._recompute_weights()
                except RequestRejected as exc:
                    if (
                        exc.reason in ("unknown_stream",
                                       "stream_out_of_order")
                        and not reopened
                    ):
                        # unknown_stream: the pinned replica no longer
                        # knows our backend session (supervisor respawned
                        # it in place, or its idle reaper fired).
                        # stream_out_of_order: the edge's and backend's
                        # seq counters drifted (e.g. an ambiguous
                        # transport timeout whose frame did or did not
                        # reach the backend) — the edge enforces client
                        # ordering itself, so a backend ordering reject
                        # can only mean drift.  Both resync the same
                        # way: re-open on the SAME replica — affinity
                        # survives, history resets.
                        reopened = True
                        try:
                            out = st.replica.stream_open(
                                width=pin.width, height=pin.height,
                                trace_id=trace_id,
                            )
                            pin.backend_sid = out["session"]
                            pin.backend_seq = 0
                            continue
                        except (ReplicaUnavailable, RequestRejected) as e2:
                            last_exc = e2
                            self._note_request_failure(st)
                            self._recompute_weights()
                    else:
                        # Backlog/decode/etc: the frame's outcome, not a
                        # replica-death signal — surface it.
                        if exc.reason == "stream_backlogged":
                            self._note_request_shed(st)
                        elif exc.reason not in _STREAM_PRE_ADMISSION:
                            # Post-admission shed: the backend consumed
                            # this seq — advance ours in lockstep or the
                            # stream wedges on stream_out_of_order.
                            pin.backend_seq += 1
                        self.stats.record_shed(exc.reason)
                        raise
                except RequestTimeout:
                    # The frame was admitted and missed its deadline
                    # downstream: the backend's seq advanced.  (A
                    # transport-level timeout that never reached the
                    # backend leaves the edge one ahead — the
                    # stream_out_of_order resync above heals that on the
                    # next frame.)
                    pin.backend_seq += 1
                    self.stats.record_timeout()
                    raise
                else:
                    pin.backend_seq += 1
                    with self._lock:
                        st.shed_strikes = 0
                    return dets, hit
            if not self._repin(pin, seq, trace_id):
                self.stats.record_shed("no_replica_available")
                raise RequestRejected(
                    "no_replica_available",
                    "stream pin lost and no routable replica left",
                ) from last_exc
        self.stats.record_failure()
        err = ServerError(
            "stream frame failed after re-pin "
            f"(stream {pin.sid}, frame {seq})"
        )
        err.__cause__ = last_exc
        raise err

    def _repin(self, pin: _StreamPin, seq: int, trace_id) -> bool:
        """Move a stream whose pinned replica died: weighted-draw a new
        replica (excluding the dead pin), open a fresh backend session,
        emit exactly ONE structured ``stream_repinned`` event (trace
        instant + sink + stderr — the ISSUE 14 emit-helper pattern)."""
        old = pin.st
        exclude = {id(old)}
        while True:
            st = self._pick(exclude)
            if st is None:
                return False
            exclude.add(id(st))
            try:
                out = st.replica.stream_open(
                    width=pin.width, height=pin.height, trace_id=trace_id
                )
            except (ReplicaUnavailable, RequestRejected) as exc:
                if isinstance(exc, ReplicaUnavailable):
                    self._note_request_failure(st)
                    self._recompute_weights()
                continue
            pin.st = st
            pin.backend_sid = out["session"]
            pin.backend_seq = 0
            pin.repins += 1
            with self._lock:
                self._stream_repins += 1
            self._emit_event(
                "stream_repinned",
                stream=pin.sid,
                from_replica=old.replica.replica_id,
                to_replica=st.replica.replica_id,
                frame=seq,
                **({"trace": trace_id} if trace_id else {}),
            )
            return True

    def stream_close(self, session_id: str) -> dict:
        """Drop the pin and close the backend session (best-effort: the
        pin is gone either way, and the replica's idle reaper backstops
        a close that never reached it)."""
        with self._lock:
            pin = self._streams.pop(session_id, None)
        if pin is None:
            raise RequestRejected("unknown_stream", session_id)
        with pin.lock:
            try:
                return pin.st.replica.stream_close(pin.backend_sid)
            except (ReplicaUnavailable, RequestRejected):
                return {}

    def _reap_stale_pins(self, now: float) -> None:
        """Drop fleet-edge pins idle past ``stream_idle_timeout_s``
        (poll-thread housekeeping; the replica-side session reaps its own
        state independently)."""
        timeout = self.config.stream_idle_timeout_s
        with self._lock:
            stale = [
                sid for sid, pin in self._streams.items()
                if now - pin.last_active > timeout
            ]
            for sid in stale:
                self._streams.pop(sid, None)
        for sid in stale:
            self._emit_event("fleet_stream_reaped", stream=sid)

    # ---- elastic membership (ISSUE 19) -----------------------------------

    def active_replica_count(self) -> int:
        """Non-drained replicas — the autoscaler's notion of capacity
        (a draining victim already stopped counting)."""
        with self._lock:
            return sum(1 for st in self._states if st.state != DRAINED)

    def add_replica(self, replica) -> None:
        """Admit a new replica at weight ZERO: it takes traffic only
        after its first successful health poll populates its load fields
        — the same admission a half-open probe applies to a readmitted
        replica, so a sick spawn never takes weight (ISSUE 19)."""
        st = _ReplicaState(replica)
        with self._lock:
            self._states.append(st)
        self._emit_event(
            "fleet_replica_joined",
            replica_id=replica.replica_id,
            version=getattr(replica, "version", "unknown"),
        )

    def begin_drain(self, replica_id: str) -> bool:
        """Administratively drain one replica (the scale-down path): no
        new traffic routes to it, pinned streams re-pin on their next
        frame, and it drops out of the occupancy aggregates AND the
        federated view immediately — capacity being reclaimed must never
        be double-counted by the control loop (ISSUE 19)."""
        with self._lock:
            st = next(
                (s for s in self._states
                 if s.replica.replica_id == replica_id), None,
            )
            if st is None or st.state == DRAINED:
                return False
            st.state = DRAINED
            st.weight = 0.0
            self._federated.pop(replica_id, None)
        self._emit_event("fleet_replica_draining", replica_id=replica_id)
        self._recompute_weights()
        return True

    def remove_replica(self, replica_id: str) -> bool:
        """Forget a replica entirely (drain finished, or the respawn
        budget abandoned its slot)."""
        with self._lock:
            st = next(
                (s for s in self._states
                 if s.replica.replica_id == replica_id), None,
            )
            if st is None:
                return False
            self._states.remove(st)
            self._federated.pop(replica_id, None)
            if self._canary is st:
                self._canary = None
        self._emit_event("fleet_replica_removed", replica_id=replica_id)
        self._recompute_weights()
        return True

    # ---- metrics federation (ISSUE 15) -----------------------------------

    def scrape_metrics_once(self) -> None:
        """One federation sweep: pull every non-drained replica's
        ``/metrics`` (``metrics_text()`` on the handle — in-process or
        HTTP) and cache the parsed samples for re-exposition.  A replica
        that fails the scrape DROPS out of the federated view (stale
        series must not masquerade as live), and handles without a
        ``metrics_text`` surface are simply skipped — federation is
        read-only and strictly optional per replica."""
        with self._lock:
            handles = [
                (st.replica.replica_id, st.replica)
                for st in self._states
                if st.state != DRAINED
            ]
        for rid, replica in handles:
            scrape = getattr(replica, "metrics_text", None)
            text = None
            if scrape is not None:
                try:
                    text = scrape()
                except Exception:
                    text = None  # a scrape can never crash the sweep
            if text is None:
                with self._lock:
                    self._federated.pop(rid, None)
                continue
            parsed = parse_exposition_samples(text)
            with self._lock:
                self._federated[rid] = parsed

    def _federation_samples(self):
        """Scrape-time collector: the federated replica series,
        replica-labeled, plus the derived fleet aggregates the SLO
        monitor's fleet-level rules evaluate."""
        with self._lock:
            fed = dict(self._federated)
        p99s: list[float] = []
        shed_total = 0.0
        for rid in sorted(fed):
            types, samples = fed[rid]
            for name, labels, value in samples:
                kind = types.get(name, "untyped")
                if kind == "summary":
                    # Re-exposed quantile series are plain samples here
                    # (the replica owns the summary's _count/_sum pair,
                    # which ride through as their own untyped families).
                    kind = "gauge"
                lab = dict(labels)
                lab["replica"] = rid
                yield (
                    name, kind, "federated from the replica's /metrics",
                    lab, value,
                )
                if (
                    name == "serve_request_latency_ms"
                    and labels.get("quantile") == "0.99"
                ):
                    p99s.append(value)
                elif name == "serve_shed_total":
                    shed_total += value
        if p99s:
            yield (
                "fleet_federated_p99_ms", "gauge",
                "worst replica-local windowed p99 across the federated "
                "scrape (the fleet-level aggregate p99 ceiling input)",
                None, round(max(p99s), 4),
            )
        if fed:
            yield (
                "fleet_federated_shed_total", "gauge",
                "requests shed across all federated replicas (sum of "
                "the replica-local serve_shed_total series)",
                None, shed_total,
            )

    def federated_snapshot(self) -> dict[str, float]:
        """The flat fleet-scope metric view (``Registry.snapshot()`` over
        the fleet registry): edge series, per-replica federated series
        keyed ``name{...,replica="<id>"}``, and the fleet aggregates —
        exactly what the SLO monitor evaluates fleet rules on."""
        return self.telemetry.snapshot()

    def dump_federated(self, path: str) -> str:
        """Write FLEET_METRICS.json: the last federated scrape per
        replica (parsed samples + TYPEs), the flat fleet snapshot, and
        the router status — the metrics half ``obs/analyze --fleet``
        consumes next to the merged trace."""
        from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
            atomic_write_json,
        )

        with self._lock:
            fed = dict(self._federated)
        doc = {
            "replicas": {
                rid: {
                    "types": dict(types),
                    "samples": [
                        [name, dict(labels), value]
                        for name, labels, value in samples
                    ],
                }
                for rid, (types, samples) in sorted(fed.items())
            },
            "snapshot": self.federated_snapshot(),
            "status": self.status(),
        }
        atomic_write_json(path, doc, indent=2, sort_keys=True)
        return path

    def _federation_run(self, hb: watchdog.Heartbeat) -> None:
        try:
            while not self._stop.wait(self.config.poll_interval_s):
                hb.beat()
                self.scrape_metrics_once()
        except BaseException as e:
            # Crash channel (thread-error-contract): a dead federation
            # thread means frozen fleet metrics and silently disarmed
            # fleet SLO rules — announce, record, re-raise.
            self._fed_error = e
            print(
                json.dumps(
                    {
                        "event": "fleet_federation_crashed",
                        "error": repr(e),
                    }
                ),
                file=sys.stderr, flush=True,
            )
            raise
        finally:
            hb.close()

    # ---- canary gate -----------------------------------------------------

    def add_canary(self, replica, start_monitor: bool = False):
        """Admit ``replica`` as the canary: it takes ``canary_weight``-
        scaled traffic while a dedicated SloMonitor watches its p99
        ratio vs the fleet baseline and its shed rate.  Returns the
        monitor (tests drive ``canary_check_once``; production passes
        ``start_monitor=True`` for the poll thread)."""
        from batchai_retinanet_horovod_coco_tpu.obs import slo as slo_lib

        if self._canary is not None:
            raise ValueError("a canary is already under evaluation")
        # A previous canary generation's monitor (rolled back — its
        # rollback handler could only request_stop from its own poll
        # thread) is fully stopped here, from a safe caller thread.
        if self._canary_monitor is not None:
            self._canary_monitor.stop()
            self._canary_monitor = None
        st = _ReplicaState(replica, is_canary=True)
        with self._lock:
            self._states.append(st)
        self._canary = st
        self._canary_outcome = None
        cfg = self.config
        self._canary_monitor = slo_lib.SloMonitor(
            self.telemetry,
            [
                slo_lib.SloRule(
                    name="canary-p99-regression",
                    metric="fleet_canary_p99_ratio",
                    op=">",
                    threshold=cfg.canary_p99_factor,
                    for_s=cfg.canary_for_s,
                    description=(
                        f"canary p99 above {cfg.canary_p99_factor}x the "
                        "fleet baseline"
                    ),
                ),
                slo_lib.SloRule(
                    name="canary-shed-rate",
                    metric="fleet_canary_shed_total",
                    delta=True,
                    op=">",
                    threshold=cfg.canary_shed_per_poll,
                    for_s=cfg.canary_for_s,
                    description=(
                        "canary shedding above "
                        f"{cfg.canary_shed_per_poll}/poll"
                    ),
                ),
            ],
            sink=self.sink,
            poll_interval=cfg.canary_poll_s,
            on_violation=self._canary_rollback,
        )
        self.poll_once()
        self._emit_event(
            "canary_started",
            replica_id=replica.replica_id,
            version=replica.version,
            weight_fraction=cfg.canary_weight,
        )
        if start_monitor:
            self._canary_monitor.start()
        return self._canary_monitor

    def canary_check_once(self, now: float | None = None) -> list[dict]:
        """One canary-gate evaluation (injectable clock — the SLO
        monitor's own anti-flap state machine underneath)."""
        if self._canary_monitor is None:
            return []
        return self._canary_monitor.check_once(now=now)

    def _canary_rollback(self, violation: dict) -> None:
        """A sustained canary breach: drain it, restore baseline weights,
        emit exactly ONE structured ``canary_rollback`` event.  The SLO
        monitor fires once per sustained breach; the outcome latch makes
        rollback terminal for this canary generation regardless."""
        with self._lock:
            if self._canary is None or self._canary_outcome is not None:
                return
            self._canary_outcome = "rolled_back"
            st = self._canary
            st.state = DRAINED
            st.weight = 0.0
            self._rollbacks += 1
            # Free the canary slot: a fixed v3 export can be admitted
            # without restarting the router (the drained replica stays
            # in _states for /fleet visibility, weight pinned 0).
            self._canary = None
        self._emit_event(
            "canary_rollback",
            replica_id=st.replica.replica_id,
            version=st.replica.version,
            rule=violation.get("rule"),
            value=violation.get("value"),
            threshold=violation.get("threshold"),
            sustained_s=violation.get("sustained_s"),
        )
        if self._canary_monitor is not None:
            # Rollback is terminal for this generation — stop the gate's
            # poll loop.  request_stop (not stop): this handler may be
            # running ON the monitor's own poll thread, which cannot
            # join itself; add_canary/close finish the join later.
            self._canary_monitor.request_stop()
        try:
            st.replica.drain(timeout_s=self.config.canary_drain_timeout_s)
        except Exception:
            pass  # the drain is best-effort; the weight is already zero
        self._recompute_weights()

    def promote_canary(self) -> None:
        """Manually graduate a green canary to full weight."""
        with self._lock:
            if self._canary is None or self._canary_outcome is not None:
                return
            self._canary_outcome = "promoted"
            st = self._canary
            st.is_canary = False
            self._canary = None
        self._emit_event(
            "canary_promoted",
            replica_id=st.replica.replica_id,
            version=st.replica.version,
        )
        if self._canary_monitor is not None:
            self._canary_monitor.stop()
            self._canary_monitor = None
        self._recompute_weights()

    # ---- observability ---------------------------------------------------

    def _emit_event(self, kind: str, **fields) -> None:
        # Shared emit layering — trace instant + sink + ONE serialized
        # stderr JSONL line — lives in obs.events.emit_event (ISSUE 20).
        emit_event(kind, sink=self.sink, **fields)

    def _canary_baseline_p99(self) -> float | None:
        """Median p99 over CLOSED non-canary replicas (the fleet
        baseline the canary regresses against)."""
        p99s = sorted(
            float(st.load["p99_ms"])
            for st in self._states
            if not st.is_canary and st.state == CLOSED
            and st.load.get("p99_ms")
        )
        if not p99s:
            return None
        mid = len(p99s) // 2
        return (
            p99s[mid] if len(p99s) % 2
            else (p99s[mid - 1] + p99s[mid]) / 2.0
        )

    def _telemetry_samples(self):
        snap = self.stats.snapshot()
        with self._lock:
            states = [
                (st.replica.replica_id, st.state, st.weight,
                 dict(st.load), st.is_canary)
                for st in self._states
            ]
            redispatches = self._redispatches
            opens = self._breaker_opens
            rollbacks = self._rollbacks
            inflight = self._inflight
            canary = self._canary
            outcome = self._canary_outcome
            streams_open = len(self._streams)
            stream_repins = self._stream_repins
        yield ("fleet_requests_completed_total", "counter",
               "requests completed through the fleet router", None,
               snap["completed"])
        yield ("fleet_requests_failed_total", "counter",
               "requests failed after exhausting re-dispatch", None,
               snap["failed"])
        yield ("fleet_requests_timeout_total", "counter",
               "requests expired at the fleet edge", None, snap["timeouts"])
        for reason, n in sorted(snap["shed"].items()):
            yield ("fleet_shed_total", "counter",
                   "requests shed at the fleet edge, by reason",
                   {"reason": reason}, n)
        yield ("fleet_redispatch_total", "counter",
               "requests retried on another replica (replica death or "
               "replica-level shed)", None,
               float(redispatches))
        yield ("fleet_breaker_open_total", "counter",
               "circuit-breaker open transitions", None, float(opens))
        yield ("fleet_canary_rollback_total", "counter",
               "canary rollbacks (exactly one per failed canary)", None,
               float(rollbacks))
        yield ("fleet_inflight", "gauge",
               "requests inside the fleet edge right now", None,
               float(inflight))
        yield ("fleet_streams_open", "gauge",
               "client streams pinned at the fleet edge (ISSUE 18)",
               None, float(streams_open))
        yield ("fleet_stream_repinned_total", "counter",
               "streams moved to another replica after pin loss", None,
               float(stream_repins))
        # Fleet-level availability (ISSUE 15): the fraction of non-
        # drained replicas whose breaker is CLOSED — the metric the
        # built-in fleet availability-floor SLO rule
        # (obs.slo.fleet_availability_rule) evaluates.
        active = [s for s in states if s[1] != DRAINED]
        closed = sum(1 for s in active if s[1] == CLOSED)
        yield ("fleet_replicas_routable", "gauge",
               "replicas with a CLOSED breaker", None, float(closed))
        yield ("fleet_replicas_total", "gauge",
               "non-drained replicas in the fleet", None,
               float(len(active)))
        if active:
            yield ("fleet_availability", "gauge",
                   "routable replicas / non-drained replicas (1.0 = the "
                   "whole fleet is healthy; the availability-floor SLO "
                   "rule watches this)", None,
                   round(closed / len(active), 4))
        # Fleet occupancy aggregates (ISSUE 19): the autoscaler's primary
        # signal, from the health-poll advertised slot fields of CLOSED
        # accepting replicas ONLY — a draining or broken replica's
        # capacity is already being reclaimed and must not be counted.
        occ: list[float] = []
        free_total = 0.0
        for rid, state, weight, load, is_canary in states:
            if state != CLOSED or not load.get("accepting", False):
                continue
            cap = float(load.get("slot_capacity") or 0)
            if cap <= 0:
                continue
            free = float(load.get("free_slots") or 0)
            inflight_r = float(load.get("inflight") or 0)
            # Claimed device slots OR queued backlog, whichever reads
            # fuller — idle = 0.0, saturated = 1.0.
            occ.append(min(1.0, max((cap - free) / cap, inflight_r / cap)))
            free_total += free
        if occ:
            yield ("fleet_occupancy", "gauge",
                   "mean live slot occupancy across routable replicas "
                   "(draining replicas excluded; the autoscale band "
                   "signal)", None,
                   round(sum(occ) / len(occ), 4))
            yield ("fleet_free_slots", "gauge",
                   "idle device slots across routable replicas", None,
                   free_total)
        for rid, state, weight, load, is_canary in states:
            yield ("fleet_replica_weight", "gauge",
                   "routing weight from advertised load fields",
                   {"replica": rid}, round(weight, 6))
            yield ("fleet_breaker_state", "gauge",
                   "0=closed 1=half_open 2=open 3=drained",
                   {"replica": rid}, _STATE_CODE[state])
            yield ("fleet_replica_draining", "gauge",
                   "1 while this replica is administratively drained "
                   "(scale-down victim or rolled-back canary)",
                   {"replica": rid}, 1.0 if state == DRAINED else 0.0)
            if load.get("p99_ms"):
                yield ("fleet_replica_p99_ms", "gauge",
                       "replica-advertised windowed p99",
                       {"replica": rid}, float(load["p99_ms"]))
        if canary is not None and outcome is None:
            base = self._canary_baseline_p99()
            c_p99 = canary.load.get("p99_ms")
            if base and c_p99:
                yield ("fleet_canary_p99_ratio", "gauge",
                       "canary p99 / fleet-baseline p99 (the canary "
                       "gate's regression metric)", None,
                       round(float(c_p99) / base, 4))
            yield ("fleet_canary_shed_total", "counter",
                   "canary-advertised lifetime sheds (gate delta rule)",
                   None, float(canary.load.get("shed_total") or 0))

    def status(self) -> dict:
        """The /fleet debugging payload: per-replica identity, breaker
        state, weight, last load fields; canary outcome; counters."""
        with self._lock:
            replicas = [
                {
                    "replica_id": st.replica.replica_id,
                    "version": st.replica.version,
                    "state": st.state,
                    "weight": round(st.weight, 6),
                    "is_canary": st.is_canary,
                    "load": dict(st.load),
                }
                for st in self._states
            ]
            out = {
                "accepting": self._accepting,
                "inflight": self._inflight,
                "redispatches": self._redispatches,
                "breaker_opens": self._breaker_opens,
                "streams_open": len(self._streams),
                "stream_repins": self._stream_repins,
                "canary_rollbacks": self._rollbacks,
                "canary_outcome": self._canary_outcome,
                "federated_replicas": sorted(self._federated),
                "federation_error": (
                    repr(self._fed_error) if self._fed_error else None
                ),
            }
        out["replicas"] = replicas
        out["stats"] = self.stats.snapshot()
        return out

    def healthz(self) -> tuple[int, dict]:
        """Fleet liveness: 200 while at least one replica is routable
        (breaker CLOSED) — a degraded fleet still serves; 503 when none
        is."""
        with self._lock:
            closed = sum(1 for st in self._states if st.state == CLOSED)
            total = len(self._states)
        payload = {
            "status": "ok" if closed else "no_replicas",
            "replicas_closed": closed,
            "replicas_total": total,
        }
        return (200 if closed else 503), payload

    # ---- poll thread + lifecycle -----------------------------------------

    def _poll_run(self, hb: watchdog.Heartbeat) -> None:
        try:
            while not self._stop.wait(self.config.poll_interval_s):
                hb.beat()
                self.poll_once()
        except BaseException as e:
            # Crash channel (thread-error-contract): a dead poller means
            # frozen weights — store it so detect() re-raises, and say so.
            self._error = e
            print(
                json.dumps(
                    {"event": "fleet_poller_crashed", "error": repr(e)}
                ),
                file=sys.stderr, flush=True,
            )
            raise
        finally:
            hb.close()

    def start_polling(self) -> "FleetRouter":
        if self._thread is not None and self._thread.is_alive():
            return self
        hb = watchdog.register("fleet-health-poll")
        self._thread = threading.Thread(
            target=self._poll_run, args=(hb,), daemon=True,
            name="fleet-health-poll",
        )
        self._thread.start()
        # The federation scrape rides the same cadence on its own thread
        # (a slow replica /metrics must not delay weight updates);
        # watchdog-registered with the crash-announce contract above.
        fed_hb = watchdog.register("fleet-metrics-scrape")
        self._fed_thread = threading.Thread(
            target=self._federation_run, args=(fed_hb,), daemon=True,
            name="fleet-metrics-scrape",
        )
        self._fed_thread.start()
        return self

    def close(self, close_replicas: bool = False) -> None:
        """Stop accepting, stop the poller and canary monitor; bounded
        and idempotent.  Spawned replica processes belong to the caller
        (the CLI kills its children); ``close_replicas`` closes the
        replica HANDLES (in-process servers) too."""
        with self._lock:
            self._accepting = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._fed_thread is not None:
            self._fed_thread.join(timeout=5)
            self._fed_thread = None
        if self._canary_monitor is not None:
            self._canary_monitor.stop()
        if close_replicas:
            for st in self._states:
                try:
                    st.replica.close()
                except Exception:
                    pass  # teardown is best-effort by design

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Fleet HTTP frontend
# ---------------------------------------------------------------------------


def serve_fleet_http(
    router: FleetRouter,
    host: str = "127.0.0.1",
    port: int = 0,
    request_timeout_s: float = 60.0,
):
    """The fleet edge as HTTP — same surface as a single replica's
    frontend, so clients and scrapers cannot tell one engine from N:

    POST /detect   → 200 detections; 503 + reason on shed; 504 on
                   deadline; 500 when every replica failed
    POST /stream/open|frame|close → the streaming session surface
                   (ISSUE 18), same wire shape as a single replica's
                   frontend — frames carry X-Retinanet-Stream and
                   X-Retinanet-Frame headers, the fleet pins each
                   stream to a replica and re-pins on replica death
    GET  /healthz  → 200 while >= 1 replica is routable, else 503
    GET  /metrics  → Prometheus text over ``router.telemetry``
    GET  /fleet    → per-replica status JSON (also /statusz)

    Returns the ``ThreadingHTTPServer``; the caller owns
    ``serve_forever()``/``shutdown()`` (the CLI below runs it).
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _json(
            self, code: int, payload: dict, trace_id: str | None = None
        ) -> None:
            if trace_id is not None:
                payload = {**payload, "trace_id": trace_id}
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if trace_id is not None:
                self.send_header(trace.TRACE_HEADER, trace_id)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib casing)
            if self.path == "/healthz":
                code, payload = router.healthz()
                self._json(code, payload)
            elif self.path == "/metrics":
                body = router.telemetry.prometheus_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path in ("/fleet", "/statusz"):
                self._json(200, router.status())
            else:
                self._json(404, {"error": "not_found"})

        def _do_stream(self, trace_id):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                if self.path == "/stream/open":
                    spec = json.loads(body) if body else {}
                    out = router.stream_open(
                        width=spec.get("width"),
                        height=spec.get("height"),
                        trace_id=trace_id,
                    )
                    self._json(200, out, trace_id=trace_id)
                elif self.path == "/stream/frame":
                    sid = self.headers.get("X-Retinanet-Stream", "")
                    try:
                        seq = int(self.headers.get("X-Retinanet-Frame", -1))
                        deadline_ms = self.headers.get(
                            "X-Retinanet-Deadline-Ms"
                        )
                        timeout_s = (
                            float(deadline_ms) / 1e3
                            if deadline_ms else request_timeout_s
                        )
                    except ValueError:
                        # Malformed header → 400 via the taxonomy
                        # mapping, not the 500 catch-all.
                        raise RequestRejected(
                            "decode_error", "malformed stream header"
                        ) from None
                    dets, hit = router.stream_frame(
                        sid, seq, body,
                        timeout_s=timeout_s,
                        trace_id=trace_id,
                    )
                    self._json(
                        200,
                        {
                            "detections": dets,
                            "frame": seq,
                            "cache_hit": hit,
                        },
                        trace_id=trace_id,
                    )
                elif self.path == "/stream/close":
                    sid = self.headers.get("X-Retinanet-Stream", "")
                    stats = router.stream_close(sid)
                    self._json(
                        200, {"closed": sid, "stats": stats},
                        trace_id=trace_id,
                    )
                else:
                    self._json(404, {"error": "not_found"})
            except RequestRejected as exc:
                if exc.reason == "unknown_stream":
                    code = 404
                elif exc.reason in ("decode_error", "stream_out_of_order"):
                    code = 400
                else:
                    code = 503
                self._json(
                    code, {"error": "rejected", "reason": exc.reason},
                    trace_id=trace_id,
                )
            except (RequestTimeout, TimeoutError):
                self._json(
                    504, {"error": "deadline_exceeded"}, trace_id=trace_id
                )
            except Exception as exc:
                self._json(
                    500, {"error": "server_error", "detail": str(exc)},
                    trace_id=trace_id,
                )

        def do_POST(self):  # noqa: N802
            if self.path.startswith("/stream/"):
                trace_id = (
                    self.headers.get(trace.TRACE_HEADER)
                    or trace.new_trace_id()
                )
                self._do_stream(trace_id)
                return
            if self.path != "/detect":
                self._json(404, {"error": "not_found"})
                return
            # The fleet-wide trace id is minted HERE (or adopted from a
            # client-supplied header) and echoed on every response —
            # the whole request tree shares it (ISSUE 15).
            trace_id = (
                self.headers.get(trace.TRACE_HEADER) or trace.new_trace_id()
            )
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                dets = router.detect(
                    body, timeout_s=request_timeout_s, trace_id=trace_id
                )
            except RequestRejected as exc:
                code = 400 if exc.reason == "decode_error" else 503
                self._json(
                    code, {"error": "rejected", "reason": exc.reason},
                    trace_id=trace_id,
                )
            except (RequestTimeout, TimeoutError):
                self._json(
                    504, {"error": "deadline_exceeded"}, trace_id=trace_id
                )
            except Exception as exc:
                self._json(
                    500, {"error": "server_error", "detail": str(exc)},
                    trace_id=trace_id,
                )
            else:
                self._json(200, {"detections": dets}, trace_id=trace_id)

        def log_message(self, *args) -> None:
            pass  # request logging is the telemetry layer's job

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True  # a wedged client can't hold exit hostage
    return httpd


# ---------------------------------------------------------------------------
# CLI: python -m batchai_retinanet_horovod_coco_tpu.serve.fleet
# ---------------------------------------------------------------------------


class _SubprocessLauncher:
    """The fleet CLI's autoscale actuator (serve/autoscale.py launcher
    protocol) over the CLI's spawn/supervision machinery: ``launch``
    forks one more serve-CLI replica through the SAME ``spawn_one``
    path the startup fleet uses (so it is supervised and budget-bounded
    like any other slot), ``terminate`` removes the victim from the
    supervised set FIRST (the supervisor must not respawn an
    intentional scale-down) and SIGTERMs it into the serve frontend's
    bounded drain, and ``reap`` reports the process gone — escalating
    to SIGKILL only past ``kill_after_s``, so a wedged drain cannot
    pin a reclaiming slot forever."""

    def __init__(self, spawn_fn, procs: dict, abandoned: set,
                 kill_after_s: float = 30.0):
        self._spawn = spawn_fn  # (rid) -> replica; registers in procs
        self._procs = procs
        self._abandoned = abandoned
        self._kill_after_s = kill_after_s
        self._seq = 0
        self._terminated: dict[str, tuple] = {}  # rid -> (proc, t0)

    def launch(self):
        rid = f"scale-{self._seq}"
        self._seq += 1
        return self._spawn(rid)

    def owns(self, rid: str) -> bool:
        return rid in self._procs

    def terminate(self, rid: str) -> None:
        rec = self._procs.pop(rid, None)
        if rec is None:
            return
        proc = rec[0]
        if proc.poll() is None:
            proc.terminate()  # SIGTERM -> the serve CLI's drain path
        self._terminated[rid] = (proc, monotonic_s())

    def reap(self, rid: str) -> bool:
        rec = self._terminated.get(rid)
        if rec is None:
            return True
        proc, t0 = rec
        if proc.poll() is None:
            if monotonic_s() - t0 > self._kill_after_s:
                proc.kill()
            return False
        self._terminated.pop(rid, None)
        return True

    def prune(self) -> list[str]:
        out = sorted(self._abandoned)
        for rid in out:
            self._abandoned.discard(rid)
        return out

    def close(self) -> None:
        """Teardown: make sure no terminated-but-straggling child
        outlives the CLI (drain already had its bounded chance)."""
        from batchai_retinanet_horovod_coco_tpu.serve.replica import (
            release_subprocess,
        )

        for rid, (proc, _t0) in list(self._terminated.items()):
            release_subprocess(proc, sigterm_timeout_s=5.0)
            self._terminated.pop(rid, None)


def build_parser():
    import argparse

    from batchai_retinanet_horovod_coco_tpu.utils.cli import add_obs_flags

    p = argparse.ArgumentParser(
        description="Fleet router over N serve replicas: health-weighted "
                    "routing, circuit breaking, fleet admission control, "
                    "SLO-gated canary rollout.",
    )
    p.add_argument("--http", type=int, required=True, metavar="PORT",
                   help="fleet frontend port (0 = ephemeral, printed)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--replica", action="append", default=[],
                   metavar="URL",
                   help="attach an already-running replica frontend "
                        "(repeatable)")
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="spawn N replica subprocesses via the serve CLI "
                        "(pinned ports; supervised unless --no-respawn)")
    p.add_argument("--export-dir", default=None,
                   help="export directory for spawned replicas "
                        "(omit with --stub-engine)")
    p.add_argument("--stub-engine", action="store_true",
                   help="spawned replicas use the stub engine (no device "
                        "work — smoke/chaos harnesses)")
    p.add_argument("--stub-delay-ms", type=float, default=None,
                   help="stub engine per-dispatch delay for spawned "
                        "replicas")
    p.add_argument("--no-respawn", action="store_true",
                   help="do not respawn dead spawned replicas")
    p.add_argument("--respawn-delay-s", type=float, default=1.0)
    p.add_argument("--respawn-budget", type=int, default=5,
                   help="respawns allowed per replica slot before the "
                        "supervisor gives up (deterministic-jitter "
                        "backoff between attempts; an exhausted slot "
                        "emits respawn_budget_exhausted once and is "
                        "left to the autoscaler)")
    # Autoscaling (ISSUE 19): a declarative policy evaluated by the
    # serve/autoscale.py control loop over the federated fleet signals.
    p.add_argument("--autoscale", action="store_true",
                   help="arm the autoscaler: scale spawned replicas "
                        "between --min-replicas and --max-replicas to "
                        "hold --target-occupancy")
    p.add_argument("--target-occupancy", default="0.25:0.75",
                   metavar="LOW:HIGH",
                   help="occupancy hysteresis band: scale up above "
                        "HIGH, down below LOW, never inside the band")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="autoscale floor (0 = scale-to-zero: an idle "
                        "fleet drains every replica and respawns on "
                        "the first request)")
    p.add_argument("--max-replicas", type=int, default=4,
                   help="autoscale ceiling (a sustained breach at the "
                        "ceiling emits capped decisions — the "
                        "fleet:underprovisioned signal)")
    p.add_argument("--autoscale-policy", default=None, metavar="FILE",
                   help="JSON AutoscalePolicy file; overrides the "
                        "individual autoscale flags entirely")
    p.add_argument("--autoscale-for-s", type=float, default=5.0,
                   help="a band breach must sustain this long before "
                        "any scale decision fires")
    p.add_argument("--autoscale-up-cooldown-s", type=float, default=10.0)
    p.add_argument("--autoscale-down-cooldown-s", type=float,
                   default=30.0)
    p.add_argument("--autoscale-interval-s", type=float, default=None,
                   help="autoscaler poll cadence (default: "
                        "--poll-interval)")
    p.add_argument("--autoscale-p99-slo-ms", type=float, default=None,
                   help="optional federated-p99 ceiling: a sustained "
                        "breach scales up even inside the occupancy "
                        "band")
    p.add_argument("--poll-interval", type=float, default=1.0,
                   help="health-poll cadence (seconds)")
    p.add_argument("--fleet-timeout-s", type=float, default=30.0,
                   help="default per-request deadline at the fleet edge")
    p.add_argument("--canary-url", default=None,
                   help="attach a running replica as the canary")
    p.add_argument("--canary-export-dir", default=None,
                   help="spawn the canary from this export directory")
    p.add_argument("--canary-stub-delay-ms", type=float, default=None,
                   help="spawn a stub-engine canary with this dispatch "
                        "delay (chaos harness: an injectably-slow canary)")
    p.add_argument("--canary-weight", type=float, default=0.25)
    p.add_argument("--canary-p99-factor", type=float, default=1.5)
    p.add_argument("--canary-for-s", type=float, default=5.0)
    p.add_argument("--canary-poll-s", type=float, default=1.0)
    p.add_argument("--shed-trip", type=int, default=3,
                   help="CONSECUTIVE request-level sheds before a "
                        "replica's breaker opens (sheds are load signals "
                        "first; raise this in harnesses that shed on "
                        "purpose so availability stays a death signal)")
    p.add_argument("--spawn-serve-args", default=None, metavar="ARGS",
                   help="extra serve-CLI arguments appended to EVERY "
                        "spawned replica, as one shell-quoted string "
                        "(e.g. '--serve-admission-queue 1'); smoke "
                        "harnesses shape replica behavior with it")
    p.add_argument("--availability-floor", type=float, default=None,
                   metavar="FRAC",
                   help="fleet SLO: fire when fleet_availability "
                        "(routable/non-drained replicas) drops below "
                        "FRAC (default 0.999 when the monitor runs — "
                        "any replica loss pages exactly once per "
                        "sustained loss)")
    # Fleet observability (ISSUE 15): --obs-trace/--obs-dir enable the
    # merged fleet trace (spawned replicas join via the env contract and
    # export their own fragments, merged at exit) + the metrics.jsonl
    # sink every fleet event lands in; --slo-rule/--obs-port run the SLO
    # monitor / status server over the FEDERATED fleet registry.
    add_obs_flags(p)
    return p


def main(argv: list[str] | None = None) -> dict:
    import shlex
    import signal

    from batchai_retinanet_horovod_coco_tpu.serve.replica import (
        HttpReplica,
        spawn_http_replica,
    )
    from batchai_retinanet_horovod_coco_tpu.utils.cli import configure_obs

    args = build_parser().parse_args(argv)
    if args.spawn and not (args.export_dir or args.stub_engine):
        raise SystemExit("--spawn needs --export-dir or --stub-engine")

    # Obs bring-up BEFORE any spawn: replica subprocesses inherit the
    # RETINANET_OBS_DIR/RETINANET_OBS_RUN env contract, self-enable
    # tracing under this run's id, and export fragments the finalize
    # below merges into ONE fleet trace.json (ISSUE 15).
    obs_dir = configure_obs(args, process_label="fleet")
    sink = None
    if obs_dir is not None:
        from batchai_retinanet_horovod_coco_tpu.obs.events import EventSink

        sink = EventSink(obs_dir, run_config=vars(args))
        watchdog.default().sink = sink

    def emit(kind: str, **fields) -> None:
        """Supervision events: stdout line (the chaos harness parses
        these) + trace instant + sink record (ISSUE 15 — replica
        lifecycle is a fleet decision like any breaker transition).
        Shared layering from obs.events.emit_event (ISSUE 20)."""
        emit_event(kind, sink=sink, file=sys.stdout, **fields)

    spawn_extra = shlex.split(args.spawn_serve_args or "")
    replicas: list = [HttpReplica(url) for url in args.replica]
    procs: dict[str, tuple] = {}  # replica_id -> (proc, port, kwargs)

    def spawn_one(rid: str, port: int | None = None):
        proc, rep = spawn_http_replica(
            rid, port=port,
            export_dir=args.export_dir,
            stub_delay_ms=args.stub_delay_ms if args.stub_engine else None,
            extra_args=spawn_extra,
        )
        port = int(rep.base_url.rsplit(":", 1)[1])
        procs[rid] = (proc, port)
        emit(
            "fleet_replica_spawned",
            replica_id=rid, pid=proc.pid, port=port,
        )
        return rep

    for k in range(args.spawn):
        replicas.append(spawn_one(f"replica-{k}"))
    if not replicas:
        raise SystemExit("no replicas: pass --replica and/or --spawn")

    config = FleetConfig(
        poll_interval_s=args.poll_interval,
        default_timeout_s=args.fleet_timeout_s,
        canary_weight=args.canary_weight,
        canary_p99_factor=args.canary_p99_factor,
        canary_for_s=args.canary_for_s,
        canary_poll_s=args.canary_poll_s,
        shed_trip=args.shed_trip,
    )
    router = FleetRouter(replicas, config, sink=sink)

    # Fleet SLO monitor over the FEDERATED registry (ISSUE 15): built-in
    # availability floor + watchdog stall, plus any --slo-rule specs —
    # the same grammar/machinery as train/serve, evaluated on
    # router.federated_snapshot()'s key space.
    slo_monitor = None
    status_server = None
    if (
        obs_dir is not None
        or getattr(args, "slo_rule", None)
        or getattr(args, "obs_port", None) is not None
    ):
        from batchai_retinanet_horovod_coco_tpu.obs import slo as slo_lib

        slo_monitor = slo_lib.SloMonitor(
            router.telemetry,
            [
                slo_lib.fleet_availability_rule(
                    args.availability_floor
                    if args.availability_floor is not None
                    else 0.999
                ),
                slo_lib.fleet_occupancy_rule(),
                slo_lib.stall_rule(),
            ]
            + [slo_lib.parse_rule(s) for s in (args.slo_rule or [])],
            sink=sink,
            poll_interval=args.slo_poll_s,
        ).start()
    if getattr(args, "obs_port", None) is not None:
        status_server = telemetry.start_http_server(
            router.telemetry, port=args.obs_port, host=args.host
        )
        print(
            f"fleet telemetry on http://{status_server.host}:"
            f"{status_server.port} (/metrics /healthz /statusz)",
            flush=True,
        )

    canary_proc = None
    if args.canary_url or args.canary_export_dir or (
        args.canary_stub_delay_ms is not None
    ):
        if args.canary_url:
            canary = HttpReplica(args.canary_url, replica_id="canary")
        else:
            canary_proc, canary = spawn_http_replica(
                "canary",
                export_dir=args.canary_export_dir,
                stub_delay_ms=args.canary_stub_delay_ms,
            )
            emit(
                "fleet_replica_spawned",
                replica_id="canary", pid=canary_proc.pid,
                port=int(canary.base_url.rsplit(":", 1)[1]),
            )
        router.add_canary(canary, start_monitor=True)

    stop_supervising = threading.Event()
    # Respawn supervision state (ISSUE 19): per-slot budgets, the slots
    # waiting out a backoff delay, and the slots the budget abandoned —
    # shared with the autoscaler's launcher, which prunes abandoned
    # slots out of the router.
    from batchai_retinanet_horovod_coco_tpu.serve.replica import (
        RespawnBudget,
    )

    budgets: dict[str, RespawnBudget] = {}
    waiting: dict[str, int] = {}  # rid -> pinned port
    abandoned: set[str] = set()

    def budget_for(rid: str) -> RespawnBudget:
        b = budgets.get(rid)
        if b is None:
            # Deterministic per-slot jitter (the breaker's seeding
            # pattern): reproducible schedules, decorrelated slots.
            b = RespawnBudget(BackoffPolicy(
                max_tries=max(1, args.respawn_budget),
                base_s=args.respawn_delay_s,
                multiplier=2.0,
                ceiling_s=30.0,
                jitter=0.1,
                seed=zlib.crc32(rid.encode()),
            ))
            budgets[rid] = b
        return b

    def note_death(rid: str, port: int, now: float) -> None:
        if budget_for(rid).note_death(now):
            waiting[rid] = port
        else:
            abandoned.add(rid)
            emit(
                "respawn_budget_exhausted",
                replica_id=rid, deaths=budgets[rid].deaths,
            )

    def supervise(hb: watchdog.Heartbeat) -> None:
        """Respawn dead spawned replicas in place (same id, same port) so
        the breaker's half-open probe readmits them — BOUNDED by a
        per-slot ``RespawnBudget`` (ISSUE 19): each death schedules the
        next respawn on a deterministic-jitter backoff, and an exhausted
        budget emits ``respawn_budget_exhausted`` exactly once and
        leaves the slot to the autoscaler (a crash-looping spawn must
        never be a tight loop)."""
        try:
            while not stop_supervising.wait(args.respawn_delay_s):
                hb.beat()
                now = monotonic_s()
                for rid, (proc, port) in list(procs.items()):
                    if proc.poll() is None:
                        b = budgets.get(rid)
                        if b is not None:
                            b.note_alive(now)
                        continue
                    cur = procs.get(rid)
                    if cur is None or cur[0] is not proc:
                        continue  # scaled down / replaced under us
                    procs.pop(rid, None)
                    emit(
                        "fleet_replica_died",
                        replica_id=rid, rc=proc.returncode,
                    )
                    note_death(rid, port, now)
                for rid, port in list(waiting.items()):
                    if not budgets[rid].ready(now):
                        continue
                    waiting.pop(rid, None)
                    try:
                        new_proc, _rep = spawn_http_replica(
                            rid, port=port,
                            export_dir=args.export_dir,
                            stub_delay_ms=(
                                args.stub_delay_ms
                                if args.stub_engine else None
                            ),
                            extra_args=spawn_extra,
                        )
                    except Exception as exc:
                        emit(
                            "fleet_respawn_failed",
                            replica_id=rid, error=repr(exc),
                        )
                        note_death(rid, port, monotonic_s())
                        continue
                    procs[rid] = (new_proc, port)
                    emit(
                        "fleet_replica_respawned",
                        replica_id=rid, pid=new_proc.pid, port=port,
                    )
        except BaseException as e:
            # Crash channel: a silently-dead supervisor means no respawns.
            print(json.dumps({
                "event": "fleet_supervisor_crashed", "error": repr(e),
            }), file=sys.stderr, flush=True)
            raise
        finally:
            hb.close()

    supervisor = None
    if procs and not args.no_respawn:
        hb = watchdog.register("fleet-supervisor")
        supervisor = threading.Thread(
            target=supervise, args=(hb,), daemon=True,
            name="fleet-supervisor",
        )
        supervisor.start()

    # Autoscaling (ISSUE 19): the declarative policy + the control loop
    # over the federated signals, actuating through the SAME spawn and
    # supervision machinery as everything above.
    autoscaler = None
    launcher = None
    if args.autoscale:
        from batchai_retinanet_horovod_coco_tpu.serve.autoscale import (
            Autoscaler,
            AutoscalePolicy,
        )

        if args.autoscale_policy:
            policy = AutoscalePolicy.from_file(args.autoscale_policy)
        else:
            low, _, high = args.target_occupancy.partition(":")
            policy = AutoscalePolicy(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                occupancy_low=float(low),
                occupancy_high=float(high or low),
                p99_slo_ms=args.autoscale_p99_slo_ms,
                for_s=args.autoscale_for_s,
                up_cooldown_s=args.autoscale_up_cooldown_s,
                down_cooldown_s=args.autoscale_down_cooldown_s,
                interval_s=(
                    args.autoscale_interval_s
                    if args.autoscale_interval_s is not None
                    else args.poll_interval
                ),
            )
        launcher = _SubprocessLauncher(spawn_one, procs, abandoned)
        autoscaler = Autoscaler(router, policy, launcher, sink=sink)
        autoscaler.start()
        emit(
            "autoscaler_armed",
            min_replicas=policy.min_replicas,
            max_replicas=policy.max_replicas,
            occupancy_band=[policy.occupancy_low, policy.occupancy_high],
            p99_slo_ms=policy.p99_slo_ms,
        )

    httpd = serve_fleet_http(
        router, args.host, args.http,
        request_timeout_s=args.fleet_timeout_s,
    )
    print(
        f"fleet serving on http://{httpd.server_address[0]}:"
        f"{httpd.server_address[1]} (POST /detect; GET /healthz /metrics "
        "/fleet)",
        flush=True,
    )

    def on_sigterm(_signum, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, on_sigterm)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        stop_supervising.set()
        if supervisor is not None:
            supervisor.join(timeout=10)
        httpd.shutdown()
        httpd.server_close()
        if obs_dir is not None:
            # Final federation sweep while the replicas are still alive,
            # then the metrics half of the fleet report (the trace half
            # merges below, after the replicas export their fragments).
            try:
                router.scrape_metrics_once()
                router.dump_federated(
                    os.path.join(obs_dir, "FLEET_METRICS.json")
                )
            except Exception as exc:
                print(
                    json.dumps(
                        {
                            "event": "fleet_metrics_dump_error",
                            "error": repr(exc)[:300],
                        }
                    ),
                    file=sys.stderr, flush=True,
                )
        if slo_monitor is not None:
            slo_monitor.stop()
        if autoscaler is not None:
            autoscaler.stop()
        if launcher is not None:
            launcher.close()
        if status_server is not None:
            status_server.close()
        router.close()
        for rid, (proc, _port) in procs.items():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except Exception:
                    proc.kill()
        if canary_proc is not None and canary_proc.poll() is None:
            canary_proc.terminate()
            try:
                canary_proc.wait(timeout=10)
            except Exception:
                canary_proc.kill()
        if sink is not None:
            sink.close()
        if obs_dir is not None:
            # Replicas SIGTERMed above exported their per-process trace
            # fragments under this run's id — the merge stitches fleet +
            # every replica into one Perfetto-loadable trace.json.
            from batchai_retinanet_horovod_coco_tpu import obs

            obs.finalize()
    status = router.status()
    print(json.dumps({"fleet_stats": status["stats"]}), flush=True)
    return status


if __name__ == "__main__":
    main()
