"""Shared serve-side vocabulary: config, request lifecycle, errors, stats.

The serve subsystem (ISSUE 4) is four small machines — admission frontend,
preprocess router, per-bucket dynamic batcher, one-behind device dispatcher
— wired by bounded queues.  This module holds what they all speak:

- ``ServeConfig`` — the frontend's knobs (coalescing deadline, queue
  bounds, worker counts, drain budget);
- ``ServeRequest`` / ``DetectionFuture`` — one request's life from
  ``submit()`` to fulfillment, with the timing fields the latency stats
  and trace spans hang off;
- the error taxonomy: every way a request can fail carries an explicit
  reason (``RequestRejected.reason``), because the load-shedding contract
  is *reject-with-reason instead of unbounded latency* — a client must be
  able to tell "retry later" (shed) from "this input is bad" (decode
  error) from "the server is broken" (worker crash, ``ServerError``);
- ``LatencyStats`` — the thread-safe completed/shed/timeout counters and
  the bounded latency window the p50/p99 numbers come from (emitted into
  the obs event sink by the frontend, reported by ``bench.py --mode
  serve``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, NamedTuple

import numpy as np

from batchai_retinanet_horovod_coco_tpu.obs.events import latency_percentiles
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


class ServeError(RuntimeError):
    """Base of everything the serve subsystem raises at the frontend."""


class RequestRejected(ServeError):
    """Admission control / load shedding: the request was NOT processed.

    ``reason`` is machine-readable: ``admission_queue_full``,
    ``bucket_queue_full``, ``shutting_down``, ``decode_error``, … — the
    shed counters key on it.
    """

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(
            f"request rejected ({reason})" + (f": {detail}" if detail else "")
        )


class RequestTimeout(ServeError):
    """The request's deadline expired before its result was produced."""


class ServerClosed(ServeError):
    """The server was closed (or drained past its budget) underneath the
    request."""


class ServerError(ServeError):
    """A serve worker thread crashed; the original exception is chained as
    ``__cause__`` (the shm-pipeline error contract: a crash re-raises at
    the FRONTEND, never a silent hang)."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Frontend knobs.  Queue bounds are the load-shedding mechanism:
    every queue is bounded and a full queue sheds (rejects) instead of
    queueing unboundedly, so overload degrades p99 into explicit 503s,
    not into minutes of invisible latency."""

    # Coalescing deadline: a partial batch fires at most this long after
    # its FIRST request reached the batcher (latency floor under light
    # load; under saturation batches fill before the deadline).
    max_delay_ms: float = 10.0
    # Continuous in-flight batching (ISSUE 14): the batcher is a slot
    # pool — requests claim slots in the batch being ASSEMBLED up to the
    # moment it dispatches, and a partial batch seals the instant the
    # device can take it (the dispatch gate) OR at the deadline,
    # whichever first, so the device never idles waiting for a "full"
    # batch.  False = the classic deadline-only coalescing (seal only at
    # full/deadline), kept alive for comparison benches and as the
    # conservative fallback; both modes run on the same slot pool and
    # produce bit-identical detections — only WHEN rows ride changes.
    continuous: bool = True
    # Bounded queues (admission = the front door; bucket = per-bucket
    # coalescing buffer; dispatch = assembled batches in flight to the
    # device, 2 = classic double buffering).
    admission_queue: int = 128
    bucket_queue: int = 64
    dispatch_depth: int = 2
    # Host decode/resize worker threads (the router).
    preprocess_workers: int = 2
    # Default per-request deadline (None = no deadline unless the caller
    # passes one to submit()).
    default_timeout_s: float | None = None
    # close(drain=True) waits this long for in-flight requests.
    drain_timeout_s: float = 30.0
    # Emit a serve_stats event (p50/p99, sheds, queue depths) into the
    # obs sink every N completed batches.
    stats_every_batches: int = 10
    # Bounded window of recent request latencies the quantiles read.
    latency_window: int = 4096


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Streaming-video session knobs (ISSUE 18, serve/stream.py).

    The frame-delta cache and the idle reaper are the two operator
    levers; everything else bounds per-session resource use so a stream
    is a polite long-lived client of the slot pool, never a starvation
    vector (RUNBOOK §21 has the sizing guidance)."""

    # Frame-delta short-circuit: a frame whose decoded pixels differ
    # from the previous frame by LESS than this mean-absolute-delta
    # (uint8 counts, averaged over every pixel) returns the previous
    # frame's detections — track ids preserved — without touching the
    # device.  0.0 disables the cache entirely: every frame rides the
    # device, and the stream is bit-identical to the single-image path
    # (PARITY §5.19 pins this).
    delta_threshold: float = 2.0
    # A session with no frame activity for this long (and nothing in
    # flight) is reaped by the delivery thread — long-lived sessions
    # must not leak on silent client death.  The manager clock is
    # injectable for tests (the SlotPool now_fn pattern).
    idle_timeout_s: float = 30.0
    # Bounded session table: opens past this shed with stream_limit.
    max_streams: int = 64
    # Bounded per-stream in-flight frames: session-aware admission —
    # one stream can hold at most this many slot-pool rows, so mixed
    # stream + single-image traffic never starves either class.
    max_inflight: int = 8
    # Track stitching (host-side IoU matching over consecutive frames).
    track_iou: float = 0.3
    # A track unmatched for this many consecutive device-served frames
    # is dropped (its id is never reused within the session).
    track_max_misses: int = 5
    # Bounded window of recent frame latencies per session (p99 source).
    latency_window: int = 2048


class DetectionFuture:
    """The caller-side handle ``submit()`` returns.

    ``result()`` blocks until the request finishes and returns its
    COCO-style detection dicts (original-image coordinates — the exact
    payload ``run_coco_eval``'s conversion produces), or raises the
    request's failure (``RequestRejected`` / ``RequestTimeout`` /
    ``ServerError``/``ServerClosed``).
    """

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result: list[dict] | None = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> list[dict]:
        if not self._event.wait(timeout):
            raise TimeoutError("detection result not ready")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result

    # internal (frontend only)
    def _set_result(self, result: list[dict]) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()


class ServeRequest:
    """One request's internal record as it moves through the stages."""

    __slots__ = (
        "id", "payload", "deadline_t", "future", "t_submit", "span",
        "trace_id", "image", "scale", "orig_wh", "bucket",
    )

    def __init__(
        self,
        request_id: int,
        payload: Any,  # np.ndarray HWC uint8, or encoded image bytes
        deadline_t: float | None,
        trace_id: str | None = None,
    ):
        self.id = request_id
        self.payload = payload
        self.deadline_t = deadline_t
        self.future = DetectionFuture()
        self.t_submit = monotonic_s()
        self.span = None  # cross-thread trace handle (frontend owns it)
        # Fleet-wide request trace id (ISSUE 15): carried in from the
        # X-Retinanet-Trace header, tagged onto the serve_request span,
        # echoed back on the HTTP response.  None on bare submits.
        self.trace_id = trace_id
        # set by the router's preprocess:
        self.image: np.ndarray | None = None
        self.scale: np.float32 = np.float32(1.0)
        self.orig_wh: tuple[int, int] = (0, 0)
        self.bucket: tuple[int, int] | None = None

    def expired(self, now: float | None = None) -> bool:
        if self.deadline_t is None:
            return False
        return (monotonic_s() if now is None else now) > self.deadline_t


class AssembledBatch(NamedTuple):
    """One padded device-ready batch (the batcher → dispatcher handoff)."""

    hw: tuple[int, int]
    images: np.ndarray  # (B, bh, bw, 3) uint8, pad rows = pad pixel
    requests: list  # the ≤B live ServeRequests, row-aligned
    scales: np.ndarray  # (B,) float32; 1.0 on pad rows
    valid: np.ndarray  # (B,) bool; False on pad rows
    t_assembled: float
    # Per live row: ms spent between slot claim and seal (ISSUE 14 —
    # the serve_slot_wait_ms telemetry source; empty on legacy callers).
    slot_wait_ms: tuple = ()


class OccupancyStats:
    """Thread-safe bounded window of per-batch device occupancy
    (live rows / padded batch size — the TResNet full-occupancy signal,
    ISSUE 14).  ``record()`` is one lock + one append; the mean/last
    summary is computed lazily at ``snapshot()`` (stats/telemetry path,
    never the request hot path)."""

    def __init__(self, window: int = 1024):
        self._lock = make_lock("serve.common.OccupancyStats._lock")
        self._window = max(16, window)
        self._values: list[float] = []
        self._batches = 0

    def record(self, occupancy: float) -> None:
        with self._lock:
            self._batches += 1
            self._values.append(float(occupancy))
            if len(self._values) > self._window:
                del self._values[: -self._window]

    def snapshot(self) -> dict:
        """{mean, last, batches} over the recent window ({} before the
        first batch)."""
        with self._lock:
            if not self._values:
                return {}
            return {
                "mean": round(sum(self._values) / len(self._values), 4),
                "last": round(self._values[-1], 4),
                "batches": self._batches,
            }


class LatencyStats:
    """Thread-safe serve counters + a bounded latency window.

    ``record()`` is one lock + one append; quantiles are computed lazily
    at ``snapshot()`` (the sink emission / stats endpoint path, never the
    request hot path).
    """

    def __init__(self, window: int = 4096):
        self._lock = make_lock("serve.common.LatencyStats._lock")
        self._window = max(16, window)
        self._latencies: list[float] = []
        self.completed = 0
        self.timeouts = 0
        self.failed = 0
        self.shed: dict[str, int] = {}

    def record(self, latency_s: float) -> None:
        with self._lock:
            self.completed += 1
            self._latencies.append(latency_s)
            if len(self._latencies) > self._window:
                del self._latencies[: -self._window]

    def record_shed(self, reason: str) -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_failure(self) -> None:
        with self._lock:
            self.failed += 1

    def window_ms(self) -> list[float]:
        """The raw recent-latency window in milliseconds (the sample set
        behind ``snapshot()``'s quantiles; ``EventSink.histogram`` input)."""
        with self._lock:
            return [v * 1e3 for v in self._latencies]

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._latencies)
            out = {
                "completed": self.completed,
                "timeouts": self.timeouts,
                "failed": self.failed,
                "shed": dict(self.shed),
                "shed_total": sum(self.shed.values()),
            }
        if lat:
            # One quantile implementation across the repo (ISSUE 8
            # satellite): the shared helper in obs/events.py; only the
            # historical "window" key name differs from its "count".
            pct = latency_percentiles(
                np.asarray(lat, dtype=np.float64) * 1e3, ps=(50, 99)
            )
            out.update(
                p50_ms=pct["p50_ms"],
                p99_ms=pct["p99_ms"],
                mean_ms=pct["mean_ms"],
                max_ms=pct["max_ms"],
                window=pct["count"],
            )
        return out
