"""Streaming video detection over the continuous batcher (ISSUE 18).

Single-image request/response (ISSUE 4) is the wrong shape for video:
a camera is a LONG-LIVED client sending ordered frames under per-frame
deadline budgets, consecutive frames are usually near-identical, and the
caller wants detections it can follow across time — not an independent
box soup per frame.  This module adds that workload as a thin layer over
the existing stack, deliberately WITHOUT touching the batcher: slots
admit rows independently (ISSUE 14's SlotPool), so a stream is just a
polite long-lived ``submit()`` client plus three session-local machines:

- **Session admission + in-order delivery** (``StreamManager``): a
  session pins its shape bucket at ``open_stream()`` (one resize target
  for the whole stream), enforces monotonic frame ordering at submit
  (``stream_out_of_order`` shed — video frames are droppable, a shed
  frame's sequence number is consumed and the client moves on), caps
  per-stream in-flight frames (``stream_backlogged`` — the session-aware
  admission that keeps one hot camera from starving single-image traffic
  or other streams), and delivers results strictly in frame order per
  stream no matter how the device interleaves batches.  Idle sessions
  are reaped on an injectable clock (the SlotPool ``now_fn`` pattern) so
  silently-dead clients can't leak session state.

- **Track stitching** (``TrackStitcher``): host-side greedy IoU matching
  of each served frame's detections against the session's live tracks —
  the same pairwise-IoU kernel the anchor matcher uses (ops/iou.py),
  run on host arrays at host scale (a handful of boxes, not 100k
  anchors).  Matched detections inherit the track id; unmatched ones
  mint a fresh id; a track unmatched for ``track_max_misses`` served
  frames is dropped.  ``track_id`` is the ONLY field stitching adds to
  a detection dict — strip it and the stream payload is byte-identical
  to the single-image path (PARITY §5.19).

- **Frame-delta cache**: before touching the device, a frame is diffed
  against the session's *reference frame* — the last frame that was
  actually dispatched.  Mean absolute pixel delta under
  ``StreamConfig.delta_threshold`` short-circuits: the previous served
  detections (track ids intact) come back without claiming a slot, and
  the saved decoded bytes are counted on the telemetry plane
  (``serve_stream_cache_hits_total`` / ``_bytes_total``).  Diffing
  against the reference (not the previous) frame makes slow drift
  converge: accumulated delta eventually crosses the threshold and
  forces a real pass.  ``delta_threshold=0`` disables the cache — every
  frame rides the device and the stream is bit-identical to sequential
  single-image serving.

Cache-hit results still flow through the in-order delivery queue: a hit
queued behind an in-flight miss resolves only after the miss lands (its
detections ARE the miss's detections).  Spans: one ``stream_session``
per session and one ``stream_frame`` per frame, both carrying the fleet
trace id so Perfetto groups a stream under its fleet request tree
(ISSUE 15's parenting convention).
"""

from __future__ import annotations

import collections
import threading
import uuid
from typing import Any

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import bucket_for_source
from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.events import latency_percentiles
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.serve.common import (
    DetectionFuture,
    RequestRejected,
    ServerClosed,
    StreamConfig,
)
from batchai_retinanet_horovod_coco_tpu.serve.router import decode_payload
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


def _xywh_to_xyxy(boxes: np.ndarray) -> np.ndarray:
    """COCO [x, y, w, h] → corner [x1, y1, x2, y2] (float64 host math)."""
    out = np.asarray(boxes, dtype=np.float64).reshape(-1, 4).copy()
    out[:, 2] += out[:, 0]
    out[:, 3] += out[:, 1]
    return out


def _pairwise_iou_host(a_xyxy: np.ndarray, b_xyxy: np.ndarray) -> np.ndarray:
    """The anchor matcher's pairwise corner IoU (ops/iou.py) evaluated on
    host arrays.  Imported lazily so the stub-only serve path never pays
    the jax import for a feature it may not use."""
    from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou

    return np.asarray(pairwise_iou(a_xyxy, b_xyxy))


class TrackStitcher:
    """Greedy IoU association of per-frame detections into stable tracks.

    One instance per stream session; single-threaded (the delivery
    thread owns it).  ``update()`` mutates the detection dicts in place,
    adding ``track_id`` — matched detections keep their track's id
    across frames, which is the whole contract a downstream consumer
    needs to draw persistent boxes.  Ids are minted monotonically per
    session and never reused.
    """

    def __init__(self, iou_threshold: float = 0.3, max_misses: int = 5):
        self.iou_threshold = float(iou_threshold)
        self.max_misses = int(max_misses)
        self._next_id = 0
        # Live tracks: {"id", "box" (xyxy), "cat", "misses"}.
        self._tracks: list[dict] = []

    def update(self, detections: list[dict]) -> None:
        """Assign ``track_id`` to every detection of one served frame."""
        matched_tracks: set[int] = set()
        if self._tracks and detections:
            det_xyxy = _xywh_to_xyxy(
                np.asarray([d["bbox"] for d in detections])
            )
            trk_xyxy = np.asarray([t["box"] for t in self._tracks])
            iou = np.array(
                _pairwise_iou_host(trk_xyxy, det_xyxy), dtype=np.float64
            )
            # Category gate: a person never continues a car's track.
            for ti, t in enumerate(self._tracks):
                for di, d in enumerate(detections):
                    if d.get("category_id") != t["cat"]:
                        iou[ti, di] = -1.0
            # Greedy best-first: repeatedly take the global best pair —
            # ties broken by (track, det) index order via argmax, so the
            # assignment is deterministic for identical inputs.
            while True:
                ti, di = np.unravel_index(np.argmax(iou), iou.shape)
                if iou[ti, di] < self.iou_threshold:
                    break
                det = detections[di]
                t = self._tracks[ti]
                det["track_id"] = t["id"]
                t["box"] = det_xyxy[di]
                t["misses"] = 0
                matched_tracks.add(ti)
                iou[ti, :] = -1.0
                iou[:, di] = -1.0
        # Unmatched detections open fresh tracks.
        for d in detections:
            if "track_id" not in d:
                tid = self._next_id
                self._next_id += 1
                d["track_id"] = tid
                self._tracks.append(
                    {
                        "id": tid,
                        "box": _xywh_to_xyxy(np.asarray([d["bbox"]]))[0],
                        "cat": d.get("category_id"),
                        "misses": 0,
                    }
                )
                matched_tracks.add(len(self._tracks) - 1)
        # Unmatched tracks age out.
        survivors = []
        for ti, t in enumerate(self._tracks):
            if ti not in matched_tracks:
                t["misses"] += 1
                if t["misses"] > self.max_misses:
                    continue
            survivors.append(t)
        self._tracks = survivors

    @property
    def live_tracks(self) -> int:
        return len(self._tracks)


class StreamFrameFuture(DetectionFuture):
    """``submit_frame``'s handle: a ``DetectionFuture`` that also says
    whether this frame was served by the delta cache (``cache_hit`` is
    final the moment ``submit_frame`` returns — the hit/miss decision is
    made at admission, not delivery)."""

    __slots__ = ("cache_hit",)

    def __init__(self, cache_hit: bool):
        super().__init__()
        self.cache_hit = cache_hit


class _FrameEntry:
    """One frame's place in a session's in-order delivery queue."""

    __slots__ = (
        "seq", "raw_future", "future", "cache_hit", "t_submit",
        "deadline_t", "span", "nbytes",
    )

    def __init__(self, seq, raw_future, future, cache_hit, t_submit,
                 deadline_t, span, nbytes):
        self.seq = seq
        self.raw_future = raw_future  # None on cache hits
        self.future = future
        self.cache_hit = cache_hit
        self.t_submit = t_submit
        self.deadline_t = deadline_t
        self.span = span
        self.nbytes = nbytes


class _Session:
    """Per-stream state.  ``lock`` guards everything mutable; the
    delivery thread and submit callers are the only writers."""

    def __init__(self, sid: str, bucket, config: StreamConfig,
                 trace_id: str | None, now: float):
        self.sid = sid
        self.bucket = bucket
        self.trace_id = trace_id
        self.lock = make_lock("serve.stream._Session.lock")
        self.next_seq = 0
        self.inflight: collections.deque[_FrameEntry] = collections.deque()
        # Seqs consumed by submit_frame whose _admit has not yet appended
        # an entry (or failed).  Delivery never pops past min(admitting):
        # a pipelined later frame that finishes admission first (e.g. a
        # cache hit overtaking a frame still in decode) must wait for the
        # earlier frame's entry, and the reaper never retires a session
        # with an admission in progress.
        self.admitting: set[int] = set()
        self.stitcher = TrackStitcher(
            iou_threshold=config.track_iou,
            max_misses=config.track_max_misses,
        )
        # Frame-delta cache state: the reference frame is the last frame
        # actually DISPATCHED (not the last frame seen), so slow drift
        # accumulates delta against the frame whose detections we keep
        # returning and eventually forces a device pass.
        self.reference: np.ndarray | None = None
        self.reference_seq = -1  # highest seq that set the reference
        self.last_dets: list[dict] = []
        self.last_active = now
        self.closed = False
        self.span = None
        # Counters (under lock).
        self.frames = 0
        self.hits = 0
        self.misses = 0
        self.errors = 0
        self.latencies_ms: list[float] = []

    def snapshot(self) -> dict:
        with self.lock:
            out = {
                "bucket": list(self.bucket),
                "frames": self.frames,
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "errors": self.errors,
                "inflight": len(self.inflight),
                "next_seq": self.next_seq,
                "live_tracks": self.stitcher.live_tracks,
            }
            lat = list(self.latencies_ms)
        pct = latency_percentiles(lat, ps=(50, 99))
        if pct:
            out.update(p50_ms=pct["p50_ms"], p99_ms=pct["p99_ms"])
        return out


class StreamManager:
    """Session table + delivery thread over one ``DetectionServer``.

    The manager never touches batcher internals: frames enter through
    the same ``server.submit()`` every single-image client uses (decoded
    pixels go in, so the served bytes are identical to the single-image
    path), and the slot pool interleaves stream rows with one-shot rows
    on claim order.  What the manager adds is the session contract:
    ordered admission, bounded per-stream in-flight, in-order delivery
    with track stitching, the frame-delta cache, and idle reaping.

    ``now_fn`` is the injectable clock (tests drive reaping without
    sleeping — the SlotPool deadline idiom).
    """

    _POLL_BUSY_S = 0.002
    _POLL_IDLE_S = 0.05

    def __init__(self, server, config: StreamConfig | None = None,
                 now_fn=monotonic_s):
        self.server = server
        self.config = config or StreamConfig()
        self._now = now_fn
        self._lock = make_lock("serve.stream.StreamManager._lock")
        self._sessions: dict[str, _Session] = {}
        self._closed = False
        # Manager-wide counters (under self._lock).
        self._frames = 0
        self._hits = 0
        self._misses = 0
        self._bytes_saved = 0
        self._reaped = 0
        self._opened = 0
        self._latencies_ms: list[float] = []
        # Pull-plane registration on the server's registry: the fleet
        # metrics federation scrapes these through /metrics for free.
        reg = getattr(server, "telemetry", None)
        if reg is not None:
            reg.register_collector(self._telemetry_samples)
            reg.histogram(
                "serve_stream_frame_latency_ms",
                "per-frame submit→deliver latency across all streams",
                source=self._latency_window,
            )
        self._stop = threading.Event()
        # watchdog: registers in _run() at thread start.
        self._thread = threading.Thread(
            target=self._run, name="serve-stream-delivery", daemon=True
        )
        self._thread.start()

    # ---- session lifecycle -----------------------------------------------

    def open_stream(self, width: int | None = None,
                    height: int | None = None,
                    trace_id: str | None = None) -> dict:
        """Open a session pinned to the shape bucket that would serve a
        ``height`` × ``width`` source (engine's first bucket when the
        client doesn't declare dimensions).  Returns ``{"session",
        "bucket"}``; sheds with ``stream_limit`` past ``max_streams``."""
        engine = self.server.engine
        if width and height:
            bucket = bucket_for_source(
                int(height), int(width),
                engine.min_side, engine.max_side, engine.buckets,
            )
        else:
            bucket = tuple(engine.buckets[0])
        sid = uuid.uuid4().hex[:12]
        now = self._now()
        with self._lock:
            if self._closed:
                raise ServerClosed("stream manager closed")
            if len(self._sessions) >= self.config.max_streams:
                raise RequestRejected(
                    "stream_limit",
                    f"{len(self._sessions)} open sessions (max "
                    f"{self.config.max_streams})",
                )
            sess = _Session(sid, bucket, self.config, trace_id, now)
            sess.span = trace.begin(
                "stream_session", stream=sid,
                bucket=f"{bucket[0]}x{bucket[1]}",
                **({"trace": trace_id} if trace_id else {}),
            )
            self._sessions[sid] = sess
            self._opened += 1
        trace.instant(
            "stream_opened", stream=sid, bucket=f"{bucket[0]}x{bucket[1]}"
        )
        return {"session": sid, "bucket": list(bucket)}

    def close_stream(self, session_id: str) -> dict:
        """Explicit close: the session stops admitting immediately;
        already-in-flight frames still deliver in order, and the session
        record is retired once its queue drains.  Returns the final
        per-session stats snapshot."""
        sess = self._get(session_id)
        summary = sess.snapshot()
        with sess.lock:
            sess.closed = True
            drained = not sess.inflight and not sess.admitting
        if drained:
            self._retire(sess, reason="closed")
        return summary

    def reap_idle(self) -> list[str]:
        """Retire every session idle past ``idle_timeout_s`` with nothing
        in flight (public so tests can drive it on a fake clock; the
        delivery thread calls it every poll)."""
        now = self._now()
        reaped = []
        with self._lock:
            candidates = list(self._sessions.values())
        for sess in candidates:
            with sess.lock:
                idle = (
                    not sess.inflight
                    and not sess.admitting
                    and not sess.closed
                    and now - sess.last_active > self.config.idle_timeout_s
                )
            if idle:
                self._retire(sess, reason="idle")
                reaped.append(sess.sid)
        return reaped

    def _retire(self, sess: _Session, reason: str) -> None:
        with self._lock:
            if self._sessions.pop(sess.sid, None) is None:
                return  # already retired by a racing path
            if reason == "idle":
                self._reaped += 1
        # Close the admission door and fail anything that slipped past
        # it: a submit racing the reaper may have fetched the session
        # before the pop above — its entry would otherwise sit on a
        # queue the delivery thread never visits again (mirrors what
        # close()/_fatal do).
        with sess.lock:
            sess.closed = True
            leftovers = list(sess.inflight)
            sess.inflight.clear()
        for entry in leftovers:
            trace.end(entry.span)
            entry.future._set_error(RequestRejected(
                "unknown_stream", f"{sess.sid} retired ({reason})"
            ))
        trace.instant("stream_session_reaped", stream=sess.sid,
                      reason=reason, frames=sess.frames)
        trace.end(sess.span)
        sess.span = None

    def _get(self, session_id: str) -> _Session:
        with self._lock:
            sess = self._sessions.get(session_id)
        if sess is None:
            raise RequestRejected("unknown_stream", session_id)
        return sess

    # ---- the frame path ---------------------------------------------------

    def submit_frame(self, session_id: str, seq: int, payload: Any,
                     timeout_s: float | None = None,
                     trace_id: str | None = None) -> DetectionFuture:
        """Admit one frame.  ``seq`` must be exactly the session's next
        expected sequence number (monotonic from 0); a frame that is
        admitted consumes its seq even if it is then shed downstream —
        video frames are droppable and the client moves on.  Returns a
        future resolving to the frame's detections (each carrying
        ``track_id``) in strict frame order per stream."""
        sess = self._get(session_id)
        now = self._now()
        trace_id = trace_id or sess.trace_id
        with sess.lock:
            if sess.closed:
                raise RequestRejected("unknown_stream",
                                      f"{session_id} closed")
            sess.last_active = now
            if seq != sess.next_seq:
                raise RequestRejected(
                    "stream_out_of_order",
                    f"got seq {seq}, expected {sess.next_seq}",
                )
            if len(sess.inflight) >= self.config.max_inflight:
                raise RequestRejected(
                    "stream_backlogged",
                    f"{len(sess.inflight)} frames in flight (max "
                    f"{self.config.max_inflight})",
                )
            # Admitted: the seq is consumed from here on, even if decode
            # or downstream admission sheds the frame.
            sess.next_seq += 1
            sess.admitting.add(seq)
        span = trace.begin(
            "stream_frame", stream=session_id, seq=seq,
            **({"trace": trace_id} if trace_id else {}),
        )
        try:
            entry = self._admit(sess, seq, payload, timeout_s, trace_id,
                                now, span)
        except BaseException:
            with sess.lock:
                sess.admitting.discard(seq)
            trace.end(span)
            raise
        self._count_frame(entry)
        return entry.future

    def _admit(self, sess: _Session, seq: int, payload: Any,
               timeout_s: float | None, trace_id: str | None,
               now: float, span) -> _FrameEntry:
        # Decode HERE (not in the router) because the delta cache needs
        # pixels before deciding whether the device is involved at all.
        # decode_payload passes ndarrays through untouched, so a miss
        # hands the router the exact array it would have decoded itself
        # — the bit-identity contract survives (PARITY §5.19).
        try:
            image = decode_payload(payload)
        except Exception as exc:
            raise RequestRejected("decode_error", str(exc)) from exc
        hit = False
        thr = self.config.delta_threshold
        with sess.lock:
            reference = sess.reference
        if thr > 0.0 and reference is not None \
                and reference.shape == image.shape:
            delta = float(
                np.mean(
                    np.abs(
                        image.astype(np.int16)
                        - reference.astype(np.int16)
                    )
                )
            )
            hit = delta < thr
        deadline_t = None if timeout_s is None else now + timeout_s
        fut = StreamFrameFuture(hit)
        if hit:
            entry = _FrameEntry(seq, None, fut, True, now, deadline_t,
                                span, int(image.nbytes))
        else:
            # The one real device path: the same submit() every
            # single-image client uses, slot-pool admission included.
            raw = self.server.submit(
                image, timeout_s=timeout_s, trace_id=trace_id
            )
            entry = _FrameEntry(seq, raw, fut, False, now, deadline_t,
                                span, int(image.nbytes))
        with sess.lock:
            sess.admitting.discard(seq)
            if sess.closed:
                # The session was retired between admission and the
                # queue append (idle reap racing this submit): without
                # this re-check the entry would land on a queue the
                # delivery thread never visits again and the future
                # would hang.  An already-dispatched raw future resolves
                # harmlessly with no waiter.
                raise RequestRejected(
                    "unknown_stream", f"{sess.sid} closed"
                )
            # Concurrent admissions can complete out of seq order (a
            # cache hit overtakes a frame still in decode): insert in
            # seq position so delivery stays strictly frame-ordered.
            q = sess.inflight
            idx = len(q)
            while idx > 0 and q[idx - 1].seq > seq:
                idx -= 1
            q.insert(idx, entry)
            if not hit and seq > sess.reference_seq:
                # Monotonic by seq: a stale miss finishing late must not
                # roll the reference back behind a newer dispatch.
                sess.reference = image
                sess.reference_seq = seq
        return entry

    def _count_frame(self, entry: _FrameEntry) -> None:
        with self._lock:
            self._frames += 1
            if entry.cache_hit:
                self._hits += 1
                self._bytes_saved += entry.nbytes
            else:
                self._misses += 1

    # ---- delivery ---------------------------------------------------------

    def _run(self) -> None:
        hb = watchdog.register(
            "serve-stream-delivery",
            details=lambda: {
                "sessions": len(self._sessions),
                "frames": self._frames,
            },
        )
        try:
            while not self._stop.is_set():
                progressed, busy = self._deliver_ready()
                self.reap_idle()
                if progressed or busy:
                    hb.beat()
                    if not progressed:
                        self._stop.wait(self._POLL_BUSY_S)
                else:
                    hb.idle()
                    self._stop.wait(self._POLL_IDLE_S)
        except BaseException as exc:
            self._fatal(exc)
        finally:
            hb.close()

    def _fatal(self, exc: BaseException) -> None:
        """Delivery-loop crash channel (thread-error-contract): refuse
        new work and re-raise in every waiting client — a frame future
        must never outlive the thread that would have resolved it."""
        with self._lock:
            self._closed = True
            sessions = list(self._sessions.values())
            self._sessions.clear()
        self._stop.set()
        for sess in sessions:
            with sess.lock:
                pending = list(sess.inflight)
                sess.inflight.clear()
                sess.closed = True
            for entry in pending:
                trace.end(entry.span)
                entry.future._set_error(exc)
            trace.end(sess.span)
            sess.span = None

    def _deliver_ready(self) -> tuple[bool, bool]:
        """One pass over every session's queue head: pop and resolve
        every entry that is ready, strictly in order.  Returns
        (progressed, anything_in_flight)."""
        progressed = False
        busy = False
        with self._lock:
            sessions = list(self._sessions.values())
        for sess in sessions:
            while True:
                with sess.lock:
                    if not sess.inflight:
                        busy = busy or bool(sess.admitting)
                        break
                    head = sess.inflight[0]
                    if sess.admitting and min(sess.admitting) < head.seq:
                        # An earlier frame is still mid-admission (decode
                        # or slot wait): its entry will insert ahead of
                        # the current head — delivering now would break
                        # strict frame order.
                        busy = True
                        break
                    if not head.cache_hit and not head.raw_future.done():
                        busy = True
                        break
                    sess.inflight.popleft()
                    resolved = self._resolve(sess, head)
                progressed = True
                if resolved is not None:
                    # Resolve OUTSIDE the session lock: result() waiters
                    # wake immediately and a slow waiter callback can't
                    # block admission.
                    entry, result, error = resolved
                    self._finish(sess, entry, result, error)
            with sess.lock:
                drained_close = (
                    sess.closed and not sess.inflight and not sess.admitting
                )
            if drained_close:
                self._retire(sess, reason="closed")
        return progressed, busy

    def _resolve(self, sess: _Session, entry: _FrameEntry):
        """Under ``sess.lock``: turn a ready entry into (entry, result,
        error), updating stitcher / cache state."""
        now = self._now()
        if entry.cache_hit:
            if entry.deadline_t is not None and now > entry.deadline_t:
                from batchai_retinanet_horovod_coco_tpu.serve.common import (
                    RequestTimeout,
                )
                return entry, None, RequestTimeout(
                    f"stream frame seq={entry.seq} deadline expired"
                )
            # The hit's payload is whatever the stream most recently
            # served — per-dict copies so callers can't mutate shared
            # session state.
            return entry, [dict(d) for d in sess.last_dets], None
        try:
            dets = entry.raw_future.result(timeout=0)
        except BaseException as exc:  # shed/timeout/server error
            sess.errors += 1
            return entry, None, exc
        sess.stitcher.update(dets)
        sess.last_dets = dets
        return entry, [dict(d) for d in dets], None

    def _finish(self, sess: _Session, entry: _FrameEntry, result, error):
        latency_ms = (self._now() - entry.t_submit) * 1e3
        with sess.lock:
            sess.frames += 1
            if entry.cache_hit:
                sess.hits += 1
            elif error is None:
                sess.misses += 1
            sess.latencies_ms.append(latency_ms)
            if len(sess.latencies_ms) > self.config.latency_window:
                del sess.latencies_ms[: -self.config.latency_window]
        with self._lock:
            self._latencies_ms.append(latency_ms)
            if len(self._latencies_ms) > 4096:
                del self._latencies_ms[:-4096]
        telemetry.record_stream_frame(
            cache_hit=entry.cache_hit, latency_ms=latency_ms
        )
        trace.end(entry.span)
        if error is not None:
            entry.future._set_error(error)
        else:
            entry.future._set_result(result)

    # ---- observability ----------------------------------------------------

    def _latency_window(self) -> list[float]:
        with self._lock:
            return list(self._latencies_ms)

    def _telemetry_samples(self):
        with self._lock:
            sessions = len(self._sessions)
            frames, hits, misses = self._frames, self._hits, self._misses
            saved, reaped = self._bytes_saved, self._reaped
        yield ("serve_stream_sessions", "gauge",
               "open streaming sessions", None, sessions)
        yield ("serve_stream_frames_total", "counter",
               "frames admitted across all streams", None, frames)
        yield ("serve_stream_cache_hits_total", "counter",
               "frames short-circuited by the frame-delta cache",
               None, hits)
        yield ("serve_stream_cache_misses_total", "counter",
               "frames dispatched to the device", None, misses)
        yield ("serve_stream_cache_bytes_total", "counter",
               "decoded bytes the delta cache kept off the device",
               None, saved)
        yield ("serve_stream_reaped_total", "counter",
               "idle sessions retired by the reaper", None, reaped)

    def status(self) -> dict:
        """The /stream status payload: manager counters + per-session
        snapshots (frames, hit rate, in-flight, live tracks, p50/p99)."""
        with self._lock:
            sessions = dict(self._sessions)
            out = {
                "sessions_open": len(sessions),
                "sessions_opened": self._opened,
                "frames": self._frames,
                "cache_hits": self._hits,
                "cache_misses": self._misses,
                "cache_bytes_saved": self._bytes_saved,
                "reaped": self._reaped,
            }
            lat = list(self._latencies_ms)
        pct = latency_percentiles(lat, ps=(50, 99))
        if pct:
            out.update(
                frame_p50_ms=pct["p50_ms"], frame_p99_ms=pct["p99_ms"]
            )
        out["streams"] = {sid: s.snapshot() for sid, s in sessions.items()}
        return out

    # ---- shutdown ---------------------------------------------------------

    def close(self) -> None:
        """Stop the delivery thread and fail every undelivered frame
        with ``ServerClosed`` (exactly-once: frames already delivered
        are untouched)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._thread.join(timeout=10.0)
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for sess in sessions:
            with sess.lock:
                pending = list(sess.inflight)
                sess.inflight.clear()
                sess.closed = True
            for entry in pending:
                trace.end(entry.span)
                entry.future._set_error(
                    ServerClosed("stream manager closed")
                )
            trace.end(sess.span)
            sess.span = None


__all__ = [
    "StreamManager",
    "StreamFrameFuture",
    "TrackStitcher",
    "StreamConfig",
]
