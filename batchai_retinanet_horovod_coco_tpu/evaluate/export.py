"""Serialized inference artifacts (the ``convert_model.py`` equivalent).

The reference ships ``bin/convert_model.py`` (SURVEY.md M3): turn a training
snapshot into a self-contained inference model (``retinanet_bbox``: forward →
decode → clip → NMS) that runs without the training code.  In this framework
inference is just another jitted function over the same params, so conversion
becomes *export*: lower the full detection program (including on-device NMS)
to serialized StableHLO via ``jax.export``, with the trained parameters baked
in as constants.  The artifact is loadable with nothing but jax — no model
code, no framework import — and can be lowered for several platforms at once
(e.g. ``("cpu", "tpu")``), the analogue of the reference's one ``.h5`` that
ran wherever Keras did.

One artifact is produced per static input shape (batch, H, W) — the price of
compiled static shapes (SURVEY.md §7.3 hard part 1); the manifest records the
shapes so callers route images to the right program, exactly as the training
pipeline routes into shape buckets.

Layout of an export directory:

    manifest.json                     shapes, detect config, class names
    detector_<H>x<W>_b<B>.stablehlo   one serialized program per bucket
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
    atomic_write_bytes,
    atomic_write_text,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
)

_MANIFEST = "manifest.json"


def _artifact_name(hw: tuple[int, int], batch_size: int) -> str:
    return f"detector_{hw[0]}x{hw[1]}_b{batch_size}.stablehlo"


def export_detector(
    state,
    model,
    image_hw: tuple[int, int],
    batch_size: int,
    config: DetectConfig = DetectConfig(),
    platforms: tuple[str, ...] | None = None,
    input_dtype: Any = jnp.uint8,
) -> bytes:
    """Serialize one detection program (params baked in) for one bucket.

    The exported callable maps ``images (B, H, W, 3) uint8`` (raw pipeline
    format; normalization happens inside, as in training) to the Detections
    tuple ``(boxes, scores, labels, valid)``.
    """
    from jax import export as jax_export

    detect = make_detect_fn(model, image_hw, config)
    # Bake the train state in as closure constants; the artifact is
    # self-contained like the reference's converted .h5.
    fn = jax.jit(lambda images: tuple(detect(state, images)))
    spec = jax.ShapeDtypeStruct((batch_size, *image_hw, 3), input_dtype)
    kwargs = {} if platforms is None else {"platforms": tuple(platforms)}
    return jax_export.export(fn, **kwargs)(spec).serialize()


def export_model(
    state,
    model,
    output_dir: str,
    buckets: tuple[tuple[int, int], ...],
    batch_size: int | tuple[int, ...] = 1,
    config: DetectConfig = DetectConfig(),
    platforms: tuple[str, ...] | None = None,
    class_names: list[str] | None = None,
    label_to_cat_id: dict[int, int] | None = None,
    image_min_side: int | None = None,
    image_max_side: int | None = None,
    version: str | None = None,
) -> str:
    """Export one detection artifact per (shape bucket, batch size) + a
    manifest.

    ``batch_size`` may be a tuple — the serve-side dynamic batcher
    (serve/) pads a partial batch up to the SMALLEST exported size that
    fits it, so exporting e.g. ``(1, 8)`` lets a lone straggler request
    run at batch 1 instead of paying a full 8-wide pad.  ``image_min_side``
    / ``image_max_side`` record the resize rule the model was evaluated
    under: a server routing raw images into buckets must use them, not its
    own defaults (manifest-driven routing, same discipline as the anchor
    config).  Returns the manifest path.
    """
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        resolve_detect_config,
    )
    from batchai_retinanet_horovod_coco_tpu.tune import schedule as schedule_lib

    os.makedirs(output_dir, exist_ok=True)
    # Resolve the schedule-dependent knobs ONCE, here: the manifest must
    # record the concrete values the artifacts were exported with (a None
    # pre_nms_size in the manifest would describe nothing), and every
    # per-bucket export below must bake in the same resolution.
    config = resolve_detect_config(config)
    batch_sizes = (
        (batch_size,) if isinstance(batch_size, int) else tuple(batch_size)
    )
    entries = []
    for hw in buckets:
        for b in batch_sizes:
            name = _artifact_name(hw, b)
            data = export_detector(
                state, model, hw, b, config, platforms=platforms
            )
            # Atomic: the manifest names this file; a torn artifact must
            # never be loadable under its published name (ISSUE 11 rule).
            atomic_write_bytes(os.path.join(output_dir, name), data)
            entries.append(
                {"file": name, "height": hw[0], "width": hw[1],
                 "batch_size": b}
            )
    manifest = {
        "format": "jax.export.stablehlo.v1",
        "input": "uint8 RGB (B, H, W, 3), raw pixels (normalization inside)",
        "output": ["boxes", "scores", "labels", "valid"],
        "artifacts": entries,
        "detect_config": {
            "score_threshold": config.score_threshold,
            "iou_threshold": config.iou_threshold,
            "pre_nms_size": config.pre_nms_size,
            "max_detections": config.max_detections,
            "nms_impl": config.nms_impl,
            "nms_block_k": config.nms_block_k,
        },
        # Where the schedule-dependent knobs above came from (ROADMAP:
        # winners are "recorded next to the export manifests"): the
        # per-device registry artifact, or the built-in defaults when the
        # exporting device is untuned.
        "schedule": schedule_lib.provenance(),
        # Anchors parameterize box decoding INSIDE the artifact; recorded so
        # the artifact is self-describing (a consumer regenerating anchors,
        # e.g. for target assignment, must use these, not the defaults).
        "anchor_config": dataclasses.asdict(config.anchor),
        # Inference-time resize rule (serve routing): raw images are
        # resized/bucketed with THESE sides, exactly as the eval pipeline
        # that produced the model's metrics did.  None on legacy exports.
        "image_min_side": image_min_side,
        "image_max_side": image_max_side,
        # Rollout identity (ISSUE 12): the serve fleet's canary gate and
        # router attribute per-replica health/weight by this; loaders
        # fall back to the export dir's basename when absent.
        "version": version,
        "class_names": class_names,
        "label_to_cat_id": (
            {str(k): v for k, v in label_to_cat_id.items()}
            if label_to_cat_id
            else None
        ),
    }
    path = os.path.join(output_dir, _MANIFEST)
    # The manifest is the export's commit record (serve/engine.from_export
    # trusts it): written atomically, and LAST — after every artifact it
    # names exists on disk.
    atomic_write_text(path, json.dumps(manifest, indent=2))
    return path


@dataclasses.dataclass
class LoadedDetector:
    """A deserialized export directory: shape-routed detection callables."""

    manifest: dict
    _fns: dict[tuple[int, int, int], Callable]

    def buckets(self) -> list[tuple[int, int, int]]:
        return sorted(self._fns)

    def bucket_shapes(self) -> list[tuple[int, int]]:
        """The distinct (H, W) buckets across all exported batch sizes."""
        return sorted({(h, w) for _b, h, w in self._fns})

    def batch_sizes(self, hw: tuple[int, int]) -> list[int]:
        """Exported batch sizes for one (H, W) bucket, ascending."""
        return sorted(b for b, h, w in self._fns if (h, w) == hw)

    def fn(self, batch_size: int, hw: tuple[int, int]):
        """The raw callable for one exact (batch, H, W) program."""
        return self._fns[(batch_size, *hw)]

    def warmup(self) -> None:
        """Run every exported program once on zeros so the deserialized
        executables are loaded/autotuned before real traffic (the serve
        engine's startup AOT warm)."""
        import jax

        for b, h, w in self.buckets():
            jax.block_until_ready(
                self._fns[(b, h, w)](np.zeros((b, h, w, 3), np.uint8))
            )

    def __call__(self, images: np.ndarray):
        """Run the artifact matching ``images.shape`` exactly."""
        b, h, w = images.shape[:3]
        fn = self._fns.get((b, h, w))
        if fn is None:
            raise ValueError(
                f"no exported program for input shape {(b, h, w)}; "
                f"available: {self.buckets()}"
            )
        return fn(images)


def load_model(output_dir: str) -> LoadedDetector:
    """Load an export directory produced by ``export_model``.

    Needs only jax — neither the model code nor the checkpoint.
    """
    from jax import export as jax_export

    with open(os.path.join(output_dir, _MANIFEST)) as f:
        manifest = json.load(f)
    fns: dict[tuple[int, int, int], Callable] = {}
    for entry in manifest["artifacts"]:
        with open(os.path.join(output_dir, entry["file"]), "rb") as f:
            exported = jax_export.deserialize(f.read())
        key = (entry["batch_size"], entry["height"], entry["width"])
        fns[key] = exported.call
    return LoadedDetector(manifest=manifest, _fns=fns)
