"""Evaluation: on-device detection + host-side COCO mAP oracle.

Replaces the reference's eval layer (SURVEY.md M3/M6/M10, call stack 3.5):
the inference "bbox model" + FilterDetections become one jitted device
function (detect.py), and pycocotools' C-backed COCOeval becomes a numpy
oracle with identical bbox semantics (coco_eval.py) since this environment
has no pycocotools.
"""

from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    CocoEval,
    EvalParams,
    StreamingCocoEval,
    evaluate_detections,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.voc_eval import (
    compute_ap,
    evaluate_detections_voc,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    collect_detections,
    coco_gt_from_dataset,
    compile_detect_fn,
    detections_to_coco,
    make_detect_fn,
    make_detect_fn_spatial,
    run_coco_eval,
)

__all__ = [
    "CocoEval",
    "DetectConfig",
    "EvalParams",
    "StreamingCocoEval",
    "coco_gt_from_dataset",
    "collect_detections",
    "compile_detect_fn",
    "compute_ap",
    "evaluate_detections_voc",
    "detections_to_coco",
    "evaluate_detections",
    "make_detect_fn",
    "make_detect_fn_spatial",
    "run_coco_eval",
]
