"""ctypes bindings for the native COCOeval kernels (native/cocoeval.cpp).

``get_kernels()`` returns (iou_matrix, match_detections) numpy-facing
callables, or None when the native library can't be built/loaded — callers
keep their pure-numpy path as the fallback and oracle.  Disable explicitly
with BATCHAI_TPU_NO_NATIVE=1 (used by the parity tests to compare paths).
"""

from __future__ import annotations

import ctypes
import os
from typing import Callable, NamedTuple

import numpy as np

_i64 = ctypes.POINTER(ctypes.c_int64)
_f64 = ctypes.POINTER(ctypes.c_double)
_u8 = ctypes.POINTER(ctypes.c_uint8)


class NativeKernels(NamedTuple):
    iou_matrix: Callable[[np.ndarray, np.ndarray, np.ndarray], np.ndarray]
    match_detections: Callable[
        [np.ndarray, np.ndarray, np.ndarray, np.ndarray],
        tuple[np.ndarray, np.ndarray, np.ndarray],
    ]


_CACHED: tuple[bool, NativeKernels | None] | None = None


def _as(arr: np.ndarray, dtype, ptr_type):
    a = np.ascontiguousarray(arr, dtype=dtype)
    return a, a.ctypes.data_as(ptr_type)


def get_kernels() -> NativeKernels | None:
    global _CACHED
    if _CACHED is not None:
        return _CACHED[1]
    if os.environ.get("BATCHAI_TPU_NO_NATIVE"):
        _CACHED = (True, None)
        return None

    from batchai_retinanet_horovod_coco_tpu.native import load_library

    # BATCHAI_TPU_NATIVE_ASAN=1: AddressSanitizer build of the kernels
    # (see tests/unit/test_native_asan.py).  Loading an ASAN .so without
    # libasan ahead of it in the link order KILLS the interpreter (the ASAN
    # runtime exits; no catchable exception), so honor the flag only when
    # libasan is visibly preloaded — otherwise warn and keep the numpy
    # fallback contract.
    sanitize = bool(os.environ.get("BATCHAI_TPU_NATIVE_ASAN"))
    if sanitize and "asan" not in os.environ.get("LD_PRELOAD", ""):
        import warnings

        warnings.warn(
            "BATCHAI_TPU_NATIVE_ASAN set but libasan is not in LD_PRELOAD; "
            "ignoring the flag (loading the ASAN .so would abort Python)",
            RuntimeWarning,
        )
        sanitize = False
    lib = load_library("cocoeval", sanitize=sanitize)
    if lib is None:
        _CACHED = (True, None)
        return None

    lib.iou_matrix_xywh.argtypes = [
        _f64, ctypes.c_int64, _f64, ctypes.c_int64, _u8, _f64,
    ]
    lib.iou_matrix_xywh.restype = None
    lib.match_detections.argtypes = [
        _f64, ctypes.c_int64, ctypes.c_int64, _f64, ctypes.c_int64,
        _u8, _u8, _i64, _i64, _u8,
    ]
    lib.match_detections.restype = None

    def iou_matrix(
        dt: np.ndarray, gt: np.ndarray, iscrowd: np.ndarray
    ) -> np.ndarray:
        D, G = len(dt), len(gt)
        out = np.zeros((D, G), dtype=np.float64)
        if D and G:
            dt_a, dt_p = _as(dt, np.float64, _f64)
            gt_a, gt_p = _as(gt, np.float64, _f64)
            cr_a, cr_p = _as(iscrowd, np.uint8, _u8)
            lib.iou_matrix_xywh(
                dt_p, D, gt_p, G, cr_p, out.ctypes.data_as(_f64)
            )
        return out

    def match_detections(
        ious: np.ndarray,
        iou_thrs: np.ndarray,
        g_ignore: np.ndarray,
        g_crowd: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        D, G = ious.shape
        T = len(iou_thrs)
        dtm = np.empty((T, D), dtype=np.int64)
        gtm = np.empty((T, G), dtype=np.int64)
        dt_ignore = np.empty((T, D), dtype=np.uint8)
        io_a, io_p = _as(ious, np.float64, _f64)
        th_a, th_p = _as(iou_thrs, np.float64, _f64)
        gi_a, gi_p = _as(g_ignore, np.uint8, _u8)
        gc_a, gc_p = _as(g_crowd, np.uint8, _u8)
        lib.match_detections(
            io_p, D, G, th_p, T, gi_p, gc_p,
            dtm.ctypes.data_as(_i64),
            gtm.ctypes.data_as(_i64),
            dt_ignore.ctypes.data_as(_u8),
        )
        return dtm, gtm, dt_ignore.astype(bool)

    kernels = NativeKernels(iou_matrix, match_detections)
    _CACHED = (True, kernels)
    return kernels
