"""PASCAL-VOC-style detection mAP (keras-retinanet ``Evaluate`` parity).

The reference library carries a second, simpler evaluation path alongside
CocoEval: ``utils/eval.py::evaluate`` + ``callbacks/eval.py::Evaluate``
(SURVEY.md M13) — per-class average precision at a single IoU threshold
(default 0.5) with all-point interpolation (the VOC2010+ method), used for
CSV/custom datasets where COCO tooling doesn't apply.  This module rebuilds
that metric on the same COCO-format gt/detection dicts the rest of the eval
stack produces, so either metric runs off one detection pass.

Semantics mirrored from the reference implementation:

- detections per class sorted by descending score; greedy matching, each gt
  box claimable once; a detection whose best IoU ≥ threshold against an
  unclaimed gt is a TP, everything else (including double detections of an
  already-claimed gt) is an FP;
- AP = sum over recall steps of the monotone precision envelope
  (all-point interpolation, NOT the 11-point VOC2007 variant);
- classes with zero ground-truth annotations are excluded from the mean and
  omitted from the per-class output (this API sees only gt/detection dicts,
  not the dataset's class universe; the reference reports such classes as
  (0.0, 0) and likewise excludes them from its mean);
- ``weighted_average`` weights the mean by per-class annotation counts
  (the callback's ``weighted_average`` flag);
- ``iscrowd=1`` ground truth is an IGNORE region (VOC's difficult-box
  semantics — the Pascal source routes difficult objects here,
  data/pascal_voc.py): it never counts as an annotation, and a detection
  whose MAX-overlap match (devkit assignment rule, all boxes considered)
  is an ignore box at ≥ threshold is neither TP nor FP; duplicates of a
  claimed real box stay FP even when an ignore box also overlaps.
"""

from __future__ import annotations

import numpy as np


def compute_ap(recall: np.ndarray, precision: np.ndarray) -> float:
    """All-point interpolated AP from monotone-enveloped precision."""
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    # Monotone non-increasing envelope, right to left.
    mpre = np.maximum.accumulate(mpre[::-1])[::-1]
    # Sum precision over the recall steps where recall changes.
    idx = np.flatnonzero(mrec[1:] != mrec[:-1])
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def _iou_matrix(dt: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Pairwise IoU of corner boxes, (D,4) x (G,4) → (D,G)."""
    ix1 = np.maximum(dt[:, None, 0], gt[None, :, 0])
    iy1 = np.maximum(dt[:, None, 1], gt[None, :, 1])
    ix2 = np.minimum(dt[:, None, 2], gt[None, :, 2])
    iy2 = np.minimum(dt[:, None, 3], gt[None, :, 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_d = (dt[:, 2] - dt[:, 0]) * (dt[:, 3] - dt[:, 1])
    area_g = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
    union = area_d[:, None] + area_g[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _to_corners(bbox: list[float]) -> list[float]:
    x, y, w, h = bbox
    return [x, y, x + w, y + h]


def evaluate_detections_voc(
    gt: list[dict],
    dt: list[dict],
    iou_threshold: float = 0.5,
    weighted_average: bool = False,
) -> dict[str, float]:
    """VOC mAP over COCO-format gt annotations and detection results.

    Input dicts use the same schema as the COCO oracle
    (``evaluate/coco_eval.py``): gt has image_id/category_id/bbox
    [x,y,w,h]/iscrowd; dt adds score.  Returns ``{"voc_mAP": float,
    "voc_AP_<cat>": float per class with annotations}``.
    """
    gt_by_class: dict[int, dict[int, np.ndarray]] = {}
    ignore_by_class: dict[int, dict[int, np.ndarray]] = {}
    counts: dict[int, int] = {}
    for ann in gt:
        cat, img = int(ann["category_id"]), int(ann["image_id"])
        if ann.get("iscrowd", 0):
            ignore_by_class.setdefault(cat, {}).setdefault(img, []).append(
                _to_corners(ann["bbox"])
            )
            continue
        gt_by_class.setdefault(cat, {}).setdefault(img, []).append(
            _to_corners(ann["bbox"])
        )
        counts[cat] = counts.get(cat, 0) + 1
    for table in (gt_by_class, ignore_by_class):
        for per_img in table.values():
            for img, boxes in per_img.items():
                per_img[img] = np.asarray(boxes, dtype=np.float64)

    dt_by_class: dict[int, list[dict]] = {}
    for det in dt:
        dt_by_class.setdefault(int(det["category_id"]), []).append(det)

    aps: dict[int, tuple[float, int]] = {}
    for cat, num_ann in counts.items():
        dets = sorted(
            dt_by_class.get(cat, ()), key=lambda d: -float(d["score"])
        )
        tp = np.zeros(len(dets))
        fp = np.zeros(len(dets))
        claimed: dict[int, np.ndarray] = {}
        cat_ignore = ignore_by_class.get(cat, {})
        for i, det in enumerate(dets):
            img = int(det["image_id"])
            dbox = np.asarray([_to_corners(det["bbox"])], dtype=np.float64)
            boxes = gt_by_class[cat].get(img)
            n_real = 0 if boxes is None else len(boxes)
            real_ious = (
                _iou_matrix(dbox, boxes)[0] if n_real else np.zeros(0)
            )
            ign = cat_ignore.get(img)
            ign_max = (
                float(_iou_matrix(dbox, ign).max())
                if ign is not None and len(ign)
                else -1.0
            )
            # VOC devkit rule: assign to the max-overlap gt over ALL boxes,
            # difficult included.  Winner difficult (≥ threshold) → neither
            # TP nor FP (tp=fp=0 leaves both cumsums — hence precision and
            # recall at every other rank — unchanged, equivalent to
            # removal).  Winner real → TP if unclaimed, else FP (a
            # duplicate of a claimed box is an FP even if a difficult box
            # also overlaps it, because the real box overlaps MORE).
            j = int(np.argmax(real_ious)) if n_real else -1
            best_real = float(real_ious[j]) if n_real else -1.0
            if ign_max >= iou_threshold and ign_max > best_real:
                continue
            if best_real >= iou_threshold:
                taken = claimed.setdefault(img, np.zeros(n_real, bool))
                if not taken[j]:
                    taken[j] = True
                    tp[i] = 1
                    continue
            fp[i] = 1
        ctp, cfp = np.cumsum(tp), np.cumsum(fp)
        recall = ctp / num_ann
        precision = ctp / np.maximum(ctp + cfp, 1e-12)
        aps[cat] = (compute_ap(recall, precision), num_ann)

    out: dict[str, float] = {}
    if aps:
        values = np.array([ap for ap, _ in aps.values()])
        weights = np.array([n for _, n in aps.values()], dtype=np.float64)
        if weighted_average:
            out["voc_mAP"] = float(np.sum(values * weights) / np.sum(weights))
        else:
            out["voc_mAP"] = float(values.mean())
    else:
        out["voc_mAP"] = 0.0
    for cat, (ap, _) in sorted(aps.items()):
        out[f"voc_AP_{cat}"] = ap
    return out
