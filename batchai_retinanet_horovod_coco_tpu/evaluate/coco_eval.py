"""COCO detection-mAP oracle: pycocotools ``COCOeval`` bbox semantics in numpy.

This environment has no pycocotools (SURVEY.md §7), so the evaluation metric
— the north-star number itself (BASELINE.json: "COCO mAP@[.5:.95] parity") —
is reimplemented here from the published COCOeval contract (SURVEY.md §7.3
hard part 4; API shape preserved locally at ``pycocotools/cocoeval.pyi``):

- IoU thresholds 0.50:0.05:0.95 (10), recall thresholds 0:0.01:1 (101-point
  interpolated AP), maxDets [1, 10, 100];
- area ranges all/small/medium/large = [0,1e10]/[0,32²]/[32²,96²]/[96²,1e10];
- greedy per-image per-category matching in descending score order, each
  detection taking the best still-unmatched gt with IoU ≥ threshold,
  crowd/out-of-range gts matchable but marked ignore;
- monotone precision envelope + searchsorted sampling at the 101 recall
  points; AP = mean over classes and IoU thresholds of sampled precision.

The class mirrors COCOeval's evaluate/accumulate/summarize triple so results
are comparable line-by-line with reference logs (SURVEY.md call stack 3.5).
Inputs are plain lists of dicts in COCO annotation/result format, decoupled
from any dataset class.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from batchai_retinanet_horovod_coco_tpu.evaluate import _native


@dataclasses.dataclass
class EvalParams:
    """Mirror of pycocotools ``Params(iouType='bbox')`` defaults."""

    iou_thrs: np.ndarray = dataclasses.field(
        default_factory=lambda: np.linspace(0.5, 0.95, 10)
    )
    rec_thrs: np.ndarray = dataclasses.field(
        default_factory=lambda: np.linspace(0.0, 1.0, 101)
    )
    max_dets: tuple[int, ...] = (1, 10, 100)
    area_rng: tuple[tuple[float, float], ...] = (
        (0.0, 1e10),
        (0.0, 32.0**2),
        (32.0**2, 96.0**2),
        (96.0**2, 1e10),
    )
    area_rng_lbl: tuple[str, ...] = ("all", "small", "medium", "large")


def bbox_iou_xywh(dt: np.ndarray, gt: np.ndarray, iscrowd: np.ndarray) -> np.ndarray:
    """Pairwise IoU of xywh boxes, crowd-aware (COCO ``maskUtils.iou`` bbox path).

    For a crowd gt the denominator is the detection area alone (a detection
    inside a crowd region counts as fully covered).
    Shapes: dt (D, 4), gt (G, 4) → (D, G).
    Dispatches to the native kernel when available; ``numpy_bbox_iou_xywh``
    is the oracle fallback (bit-identical, tests/unit/test_native_cocoeval.py).
    """
    if len(dt) == 0 or len(gt) == 0:
        return np.zeros((len(dt), len(gt)), dtype=np.float64)
    kernels = _native.get_kernels()
    if kernels is not None:
        return kernels.iou_matrix(dt, gt, iscrowd)
    return numpy_bbox_iou_xywh(dt, gt, iscrowd)


def numpy_bbox_iou_xywh(
    dt: np.ndarray, gt: np.ndarray, iscrowd: np.ndarray
) -> np.ndarray:
    """The pure-numpy IoU oracle (see ``bbox_iou_xywh``)."""
    if len(dt) == 0 or len(gt) == 0:
        return np.zeros((len(dt), len(gt)), dtype=np.float64)
    dx1, dy1 = dt[:, 0], dt[:, 1]
    dx2, dy2 = dt[:, 0] + dt[:, 2], dt[:, 1] + dt[:, 3]
    gx1, gy1 = gt[:, 0], gt[:, 1]
    gx2, gy2 = gt[:, 0] + gt[:, 2], gt[:, 1] + gt[:, 3]
    iw = np.clip(
        np.minimum(dx2[:, None], gx2[None, :]) - np.maximum(dx1[:, None], gx1[None, :]),
        0.0,
        None,
    )
    ih = np.clip(
        np.minimum(dy2[:, None], gy2[None, :]) - np.maximum(dy1[:, None], gy1[None, :]),
        0.0,
        None,
    )
    inter = iw * ih
    d_area = (dt[:, 2] * dt[:, 3])[:, None]
    g_area = (gt[:, 2] * gt[:, 3])[None, :]
    union = np.where(iscrowd[None, :].astype(bool), d_area, d_area + g_area - inter)
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def numpy_match_detections(
    ious: np.ndarray,
    iou_thrs: np.ndarray,
    g_ignore: np.ndarray,
    g_crowd: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The pure-numpy greedy matcher oracle (COCOeval ``evaluateImg`` core).

    Dets must be score-sorted, gts ignore-sorted (non-ignored first) —
    the layout ``CocoEval._evaluate_img`` establishes.  Returns
    (dtm (T, D), gtm (T, G), dt_ignore (T, D)); the native kernel
    (native/cocoeval.cpp) is bit-identical to this function.
    """
    D, G = ious.shape
    T = len(iou_thrs)
    gtm = -np.ones((T, G), dtype=np.int64)  # index of matching det
    dtm = -np.ones((T, D), dtype=np.int64)  # index of matching gt
    dt_ignore = np.zeros((T, D), dtype=bool)

    for t, thr in enumerate(iou_thrs):
        for dind in range(D):
            best = min(thr, 1.0 - 1e-10)
            m = -1
            for gind in range(G):
                # Gt already claimed at this threshold (crowds may rematch).
                if gtm[t, gind] >= 0 and not g_crowd[gind]:
                    continue
                # Gts are sorted ignore-last: once we have a real match,
                # stop before the ignore region.
                if m > -1 and not g_ignore[m] and g_ignore[gind]:
                    break
                if ious[dind, gind] < best:
                    continue
                best = ious[dind, gind]
                m = gind
            if m == -1:
                continue
            dtm[t, dind] = m
            gtm[t, m] = dind
            dt_ignore[t, dind] = g_ignore[m]
    return dtm, gtm, dt_ignore


class CocoEval:
    """bbox-only COCOeval: ``evaluate() → accumulate() → summarize()``.

    ``gt_anns``: COCO annotation dicts (image_id, category_id, bbox xywh,
    area, iscrowd, optional ignore).  ``dt_anns``: COCO result dicts
    (image_id, category_id, bbox xywh, score).  ``img_ids`` fixes the
    evaluated image set (images with no gt still contribute false positives,
    as in pycocotools when the gt index knows the image).
    """

    def __init__(
        self,
        gt_anns: list[dict],
        dt_anns: list[dict],
        img_ids: list[int] | None = None,
        params: EvalParams | None = None,
    ):
        self.params = params or EvalParams()
        # ``_prepare`` caches per (img, cat) with dets truncated at
        # max_dets[-1]; ``accumulate`` then re-slices ``[:max_det]`` per M
        # entry.  Both steps (like pycocotools itself) are only correct when
        # max_dets is ascending — reject the silent-wrong-scores case.
        if list(self.params.max_dets) != sorted(self.params.max_dets):
            raise ValueError(
                f"EvalParams.max_dets must be ascending, got "
                f"{list(self.params.max_dets)}"
            )
        if img_ids is None:
            img_ids = sorted(
                {a["image_id"] for a in gt_anns} | {a["image_id"] for a in dt_anns}
            )
        self.img_ids = list(img_ids)
        self.cat_ids = sorted(
            {a["category_id"] for a in gt_anns} | {a["category_id"] for a in dt_anns}
        )

        self._gts: dict[tuple[int, int], list[dict]] = {}
        self._dts: dict[tuple[int, int], list[dict]] = {}
        img_set = set(self.img_ids)
        for a in gt_anns:
            if a["image_id"] in img_set:
                self._gts.setdefault((a["image_id"], a["category_id"]), []).append(a)
        for a in dt_anns:
            if a["image_id"] in img_set:
                self._dts.setdefault((a["image_id"], a["category_id"]), []).append(a)

        self.eval_imgs: dict[tuple[int, int, int], dict | None] = {}
        self._prepared: dict[tuple[int, int], tuple | None] = {}
        self.eval: dict = {}
        self.stats = np.zeros(12)

    # -- evaluate ----------------------------------------------------------

    def _prepare(self, img_id: int, cat_id: int, max_det: int) -> tuple | None:
        """Score-sort dets and compute the IoU matrix ONCE per (img, cat).

        The result is shared by all four area ranges (pycocotools'
        ``computeIoU`` cache); ious are in (score-sorted det) × (original gt)
        order.
        """
        key = (img_id, cat_id)
        if key in self._prepared:
            return self._prepared[key]
        gt = self._gts.get(key, [])
        dt = self._dts.get(key, [])
        if not gt and not dt:
            self._prepared[key] = None
            return None
        d_scores = np.array([d["score"] for d in dt], dtype=np.float64)
        d_order = np.argsort(-d_scores, kind="stable")[:max_det]
        dt = [dt[i] for i in d_order]
        g_boxes = np.array([g["bbox"] for g in gt], dtype=np.float64).reshape(-1, 4)
        d_boxes = np.array([d["bbox"] for d in dt], dtype=np.float64).reshape(-1, 4)
        g_crowd = np.array([bool(g.get("iscrowd", 0)) for g in gt], dtype=bool)
        ious = bbox_iou_xywh(d_boxes, g_boxes, g_crowd)
        prepared = (gt, dt, d_boxes, ious)
        self._prepared[key] = prepared
        return prepared

    def _evaluate_img(
        self, img_id: int, cat_id: int, area_rng: tuple[float, float], max_det: int
    ) -> dict | None:
        p = self.params
        prepared = self._prepare(img_id, cat_id, max_det)
        if prepared is None:
            return None
        gt, dt, d_boxes, ious_raw = prepared

        g_ignore = np.array(
            [
                bool(g.get("ignore", 0))
                or bool(g.get("iscrowd", 0))
                or g["area"] < area_rng[0]
                or g["area"] > area_rng[1]
                for g in gt
            ],
            dtype=bool,
        )
        # Non-ignored gts first (stable), matching pycocotools' argsort.
        g_order = np.argsort(g_ignore, kind="stable")
        gt = [gt[i] for i in g_order]
        g_ignore = g_ignore[g_order]
        g_crowd = np.array([bool(g.get("iscrowd", 0)) for g in gt], dtype=bool)
        ious = ious_raw[:, g_order] if len(gt) else ious_raw

        D, G = len(dt), len(gt)
        iou_thrs = np.asarray(p.iou_thrs, dtype=np.float64)
        kernels = _native.get_kernels()
        if kernels is not None and G:
            dtm, gtm, dt_ignore = kernels.match_detections(
                np.ascontiguousarray(ious), iou_thrs, g_ignore, g_crowd
            )
        else:
            dtm, gtm, dt_ignore = numpy_match_detections(
                np.asarray(ious, dtype=np.float64).reshape(D, G),
                iou_thrs, g_ignore, g_crowd,
            )

        # Unmatched dets whose own area is outside the range are ignored too.
        d_area = d_boxes[:, 2] * d_boxes[:, 3]
        d_out = (d_area < area_rng[0]) | (d_area > area_rng[1])
        dt_ignore |= (dtm == -1) & d_out[None, :]

        return {
            "dt_scores": np.array([d["score"] for d in dt], dtype=np.float64),
            "dt_matched": dtm >= 0,
            "dt_ignore": dt_ignore,
            "num_gt": int((~g_ignore).sum()),
        }

    def evaluate_image(self, img_id: int) -> None:
        """Fill ``eval_imgs`` for ONE image across all (category, area)
        cells — the unit both ``evaluate`` and the streaming scorer
        (``StreamingCocoEval``) are built from, so their matching can
        never diverge."""
        p = self.params
        max_det = p.max_dets[-1]
        for c, cat_id in enumerate(self.cat_ids):
            for a, area_rng in enumerate(p.area_rng):
                self.eval_imgs[(c, a, img_id)] = self._evaluate_img(
                    img_id, cat_id, area_rng, max_det
                )

    def evaluate(self) -> None:
        for img_id in self.img_ids:
            self.evaluate_image(img_id)

    # -- accumulate --------------------------------------------------------

    def accumulate(self) -> None:
        p = self.params
        T, R = len(p.iou_thrs), len(p.rec_thrs)
        K, A, M = len(self.cat_ids), len(p.area_rng), len(p.max_dets)
        precision = -np.ones((T, R, K, A, M))
        recall = -np.ones((T, K, A, M))

        for k in range(K):
            for a in range(A):
                imgs = [
                    e
                    for img_id in self.img_ids
                    if (e := self.eval_imgs.get((k, a, img_id))) is not None
                ]
                if not imgs:
                    continue
                for m, max_det in enumerate(p.max_dets):
                    scores = np.concatenate([e["dt_scores"][:max_det] for e in imgs])
                    # Stable global sort by descending score (mergesort, as
                    # in pycocotools, keeps cross-refactor determinism).
                    order = np.argsort(-scores, kind="mergesort")
                    matched = np.concatenate(
                        [e["dt_matched"][:, :max_det] for e in imgs], axis=1
                    )[:, order]
                    ignored = np.concatenate(
                        [e["dt_ignore"][:, :max_det] for e in imgs], axis=1
                    )[:, order]
                    npig = sum(e["num_gt"] for e in imgs)
                    if npig == 0:
                        continue
                    tps = np.cumsum(matched & ~ignored, axis=1, dtype=np.float64)
                    fps = np.cumsum(~matched & ~ignored, axis=1, dtype=np.float64)
                    for t in range(T):
                        tp, fp = tps[t], fps[t]
                        nd = len(tp)
                        rc = tp / npig
                        pr = tp / np.maximum(tp + fp, np.spacing(1))
                        recall[t, k, a, m] = rc[-1] if nd else 0.0
                        # Monotone envelope: precision at recall r is the max
                        # precision at any recall ≥ r.
                        pr = np.maximum.accumulate(pr[::-1])[::-1]
                        inds = np.searchsorted(rc, p.rec_thrs, side="left")
                        q = np.zeros(R)
                        valid = inds < nd
                        q[valid] = pr[inds[valid]]
                        precision[t, :, k, a, m] = q

        self.eval = {"precision": precision, "recall": recall}

    # -- summarize ---------------------------------------------------------

    def _summarize(
        self,
        ap: bool,
        iou_thr: float | None = None,
        area: str = "all",
        max_dets: int = 100,
    ) -> float:
        p = self.params
        a = p.area_rng_lbl.index(area)
        m = p.max_dets.index(max_dets)
        if ap:
            s = self.eval["precision"]
            if iou_thr is not None:
                s = s[np.where(np.isclose(p.iou_thrs, iou_thr))[0]]
            s = s[:, :, :, a, m]
        else:
            s = self.eval["recall"]
            if iou_thr is not None:
                s = s[np.where(np.isclose(p.iou_thrs, iou_thr))[0]]
            s = s[:, :, a, m]
        valid = s[s > -1]
        return float(valid.mean()) if valid.size else -1.0

    def summarize(self) -> np.ndarray:
        """The 12 standard COCO stats; stats[0] is mAP@[.5:.95]."""
        self.stats = np.array(
            [
                self._summarize(True),
                self._summarize(True, iou_thr=0.5),
                self._summarize(True, iou_thr=0.75),
                self._summarize(True, area="small"),
                self._summarize(True, area="medium"),
                self._summarize(True, area="large"),
                self._summarize(False, max_dets=1),
                self._summarize(False, max_dets=10),
                self._summarize(False, max_dets=100),
                self._summarize(False, area="small"),
                self._summarize(False, area="medium"),
                self._summarize(False, area="large"),
            ]
        )
        return self.stats


_STAT_NAMES = (
    "AP", "AP50", "AP75", "APsmall", "APmedium", "APlarge",
    "AR1", "AR10", "AR100", "ARsmall", "ARmedium", "ARlarge",
)


class StreamingCocoEval:
    """Incremental ``CocoEval``: feed detections batch-by-batch as they
    come off the device; the per-image greedy matching (the dominant host
    cost of an eval pass — O(images × categories × thresholds) of
    numpy/C++ work) runs AS SOON AS an image's detections are complete,
    instead of all at once after the last batch.  The pipelined
    ``run_coco_eval`` (evaluate/detect.py) runs this inside its consumer
    thread, overlapping scoring with device NMS of later batches.

    Result-identical to the one-shot path, by construction: per-image
    evaluation is independent across images (``_evaluate_img`` touches only
    that image's annotations), and ``finish()`` runs the exact same
    ``accumulate``/``summarize`` over the same ``eval_imgs`` table.  The
    category list may be a SUPERSET of the categories that end up appearing
    (it must be fixed before matching starts): categories with neither gt
    nor detections evaluate to ``None`` everywhere and are excluded by
    ``accumulate``/``summarize`` exactly as absent categories are, so the
    stats match ``evaluate_detections`` bit-for-bit
    (tests/unit/test_eval_pipeline.py pins this on randomized inputs).

    Contract: ``add(dts, done_img_ids)`` marks images COMPLETE — every
    detection for those images must be in this or an earlier call (the
    eval pipeline satisfies this trivially: each image lives in exactly one
    batch).  Detections for images already marked done are rejected loudly
    rather than silently dropped from the score.
    """

    def __init__(
        self,
        gt_anns: list[dict],
        img_ids: list[int],
        cat_ids: list[int] | None = None,
        params: EvalParams | None = None,
    ):
        self._ev = CocoEval(gt_anns, [], img_ids=img_ids, params=params)
        if cat_ids is not None:
            self._ev.cat_ids = sorted(set(self._ev.cat_ids) | set(cat_ids))
        self._img_set = set(self._ev.img_ids)
        self._done: set[int] = set()
        self._finished = False

    def add(self, dt_anns: list[dict], done_img_ids) -> None:
        """Register a batch of detections and match the completed images."""
        if self._finished:
            raise RuntimeError("add() after finish()")
        for a in dt_anns:
            img_id = a["image_id"]
            if img_id not in self._img_set:
                continue
            if img_id in self._done:
                raise ValueError(
                    f"detections for image {img_id} arrived after it was "
                    "marked complete — they would be silently excluded "
                    "from the score"
                )
            self._ev._dts.setdefault((img_id, a["category_id"]), []).append(a)
        for img_id in done_img_ids:
            img_id = int(img_id)
            if img_id in self._done or img_id not in self._img_set:
                continue
            self._ev.evaluate_image(img_id)
            self._done.add(img_id)

    def finish(self) -> dict[str, float]:
        """Match remaining images (gt-only / never streamed), then
        accumulate + summarize → the same named stats dict as
        ``evaluate_detections``."""
        if not self._finished:
            for img_id in self._ev.img_ids:
                if img_id not in self._done:
                    self._ev.evaluate_image(img_id)
                    self._done.add(img_id)
            self._ev.accumulate()
            self._ev.summarize()
            self._finished = True
        return dict(zip(_STAT_NAMES, (float(s) for s in self._ev.stats)))


def evaluate_detections(
    gt_anns: list[dict],
    dt_anns: list[dict],
    img_ids: list[int] | None = None,
) -> dict[str, float]:
    """One-call evaluate/accumulate/summarize → named stats dict."""
    ev = CocoEval(gt_anns, dt_anns, img_ids=img_ids)
    ev.evaluate()
    ev.accumulate()
    stats = ev.summarize()
    return dict(zip(_STAT_NAMES, (float(s) for s in stats)))
