"""On-device detection: forward → decode → clip → batched NMS, one XLA program.

Replaces the reference's separate "inference model" conversion step and its
``Anchors → RegressBoxes → ClipBoxes → FilterDetections`` layer stack
(SURVEY.md M3/M6, call stack 3.5, ``bin/convert_model.py``): here inference
is just another jitted function over the same train-state params, with the
whole post-processing (sigmoid, top-k pre-select, class-masked NMS) running
on the TPU per BASELINE.json configs[4] ("on-device batched NMS").

``run_coco_eval`` is the dataset-level driver (the ``CocoEval`` callback /
``evaluate_coco()`` equivalent, SURVEY.md M10): stream the eval pipeline,
detect per static shape bucket (one compiled program each), rescale boxes to
original image coordinates on host, and hand COCO-format results to the
numpy mAP oracle (evaluate/coco_eval.py).

Since ISSUE 2 the driver is a THREE-STAGE PIPELINE (default; the strictly
sequential path survives as ``pipelined=False`` and stays bit-identical):

1. **device prefetch** — the shared ``prefetch_map`` helper
   (data/prefetch.py, the train loop's double-buffering machinery) moves
   eval batches host→device up to ``device_prefetch`` batches ahead, so
   detect compute overlaps the next batch's decode + DMA;
2. **one-behind async dispatch** — the jitted detect program for batch N is
   dispatched before batch N−1's results are pulled, so the host-side
   ``device_get`` + box rescale + COCO-format conversion of batch N−1
   overlap batch N's on-device NMS;
3. **background scoring consumer** — conversion and (single-process)
   incremental COCOeval matching (``StreamingCocoEval``) run in a consumer
   thread behind a bounded queue with the shm-pipeline's error contract:
   a consumer crash re-raises in the driver, ``close()`` never hangs.

EVALBENCH.json is the committed perf record of this path (``bench.py
--mode eval``; ``make evalbench-check`` is the regression tripwire).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu.data import pipeline as pipeline_lib
from batchai_retinanet_horovod_coco_tpu.data.coco import CocoDataset
from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import evaluate_detections
from batchai_retinanet_horovod_coco_tpu.evaluate.voc_eval import (
    evaluate_detections_voc,
)
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
from batchai_retinanet_horovod_coco_tpu.ops import boxes as boxes_lib
from batchai_retinanet_horovod_coco_tpu.ops import nms as nms_lib
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.train.state import model_variables


@dataclasses.dataclass(frozen=True)
class DetectConfig:
    """FilterDetections-equivalent knobs (reference defaults, SURVEY.md M6).

    Since ISSUE 6 the performance knobs — ``pre_nms_size``, the NMS
    backend, and its block shape — are SCHEDULE-RESOLVED: ``None`` means
    "look the winner up in the per-device schedule registry"
    (tune/schedule.py; the built-in defaults reproduce the hand-picked
    values every consumer shipped with).  An explicit value pins the knob
    regardless of the registry.  Resolution happens once per compile in
    :func:`resolve_detect_config` — the registry lookup is cached and
    stable for the process lifetime, so serve/eval never recompile at
    request time.
    """

    score_threshold: float = 0.05
    iou_threshold: float = 0.5
    # None = schedule-resolved (built-in default 1000).  NOTE: unlike the
    # backend knobs below, this one CHANGES DETECTION SEMANTICS (fewer
    # candidates survive to NMS) — see tune/candidates.py.
    pre_nms_size: int | None = None
    max_detections: int = 300
    # NMS suppression backend: None = schedule-resolved ("xla" unless the
    # device's committed schedule names "pallas"); "xla" | "pallas" pins.
    nms_impl: str | None = None
    # (K, K) IoU tile width of the Pallas kernel: None = schedule-resolved.
    nms_block_k: int | None = None
    # Interpreter-mode Pallas (CPU tests of the fused suppression path).
    nms_interpret: bool = False
    codec: boxes_lib.BoxCodecConfig = boxes_lib.BoxCodecConfig()
    anchor: anchors_lib.AnchorConfig = anchors_lib.AnchorConfig()


def resolve_detect_config(
    config: DetectConfig, device_kind: str | None = None
) -> DetectConfig:
    """Fill every schedule-resolved field; returns a fully concrete config.

    The consumer entrypoint for the tune/ registry on the detect side:
    ``_detect_body`` calls it at trace time (host-side, once per bucket
    compile), so the executable bakes the winning ``pre_nms_size`` /
    backend / block shape in.  Unknown ``device_kind`` falls back to the
    built-in defaults with one loud ``schedule_fallback`` event
    (tune/schedule.py), never a crash.
    """
    if config.nms_impl is not None and config.nms_impl not in ("xla", "pallas"):
        # Validate BEFORE the fully-pinned early return: a typo'd impl on
        # a fully concrete config must raise here, not silently take the
        # XLA branch in nms_fn_for's == "pallas" comparison.
        raise ValueError(
            f"nms_impl must be 'xla' or 'pallas', got {config.nms_impl!r}"
        )
    if (
        config.pre_nms_size is not None
        and config.nms_impl is not None
        and config.nms_block_k is not None
    ):
        return config
    from batchai_retinanet_horovod_coco_tpu.tune import schedule as schedule_lib

    entry = schedule_lib.lookup(device_kind)["nms"]
    impl = config.nms_impl or str(entry.get("impl", "xla"))
    if impl == "auto":  # NMS has no backend-conditional default: auto = xla
        impl = "xla"
    if impl not in ("xla", "pallas"):
        raise ValueError(f"nms_impl must be 'xla' or 'pallas', got {impl!r}")
    return dataclasses.replace(
        config,
        pre_nms_size=(
            config.pre_nms_size
            if config.pre_nms_size is not None
            else int(entry.get("pre_nms_size", 1000))
        ),
        nms_impl=impl,
        nms_block_k=(
            config.nms_block_k
            if config.nms_block_k is not None
            else int(entry.get("block_k", 256))
        ),
    )


def nms_fn_for(
    config: DetectConfig,
) -> Callable[[jnp.ndarray, jnp.ndarray], nms_lib.Detections]:
    """``(boxes (B, A, 4), scores (B, A, K)) → Detections`` for a RESOLVED
    config — the one place the XLA-vs-Pallas suppression dispatch lives
    (bench.py's postprocess tripwire uses it too, so the tuned winner is
    what the committed number measures)."""
    config = resolve_detect_config(config)
    if config.nms_impl == "pallas":
        from batchai_retinanet_horovod_coco_tpu.ops.pallas import (
            nms as pallas_nms,
        )

        def nms(boxes, scores):
            return pallas_nms.batched_multiclass_nms_pallas(
                boxes,
                scores,
                score_threshold=config.score_threshold,
                iou_threshold=config.iou_threshold,
                pre_nms_size=config.pre_nms_size,
                max_detections=config.max_detections,
                block_k=config.nms_block_k,
                interpret=config.nms_interpret,
            )
    else:

        def nms(boxes, scores):
            return nms_lib.batched_multiclass_nms(
                boxes,
                scores,
                score_threshold=config.score_threshold,
                iou_threshold=config.iou_threshold,
                pre_nms_size=config.pre_nms_size,
                max_detections=config.max_detections,
            )

    return nms


def _detect_body(
    model, image_hw: tuple[int, int], config: DetectConfig
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """The ONE detection pipeline every factory wraps: normalize → forward →
    sigmoid → decode → clip → batched NMS.  Shared so the batch-sharded and
    spatially-sharded paths can never drift from the single-device one.

    The NMS backend dispatch lives here too (schedule-resolved, see
    :func:`resolve_detect_config`): ``impl == "pallas"`` swaps the
    suppression stage for the fused blocked kernel (ops/pallas/nms.py),
    which shares candidate selection and compaction with the XLA path and
    is bit-identical to it (tests/unit/test_pallas_nms.py)."""
    config = resolve_detect_config(config)
    anchors = jnp.asarray(
        anchors_lib.anchors_for_image_shape(image_hw, config.anchor)
    )
    nms = nms_fn_for(config)

    def detect(state, images: jnp.ndarray) -> nms_lib.Detections:
        # uint8 batches normalize on device (data/pipeline.normalize_images).
        images = pipeline_lib.normalize_images(images)
        outputs = model.apply(model_variables(state), images, train=False)
        scores = jax.nn.sigmoid(outputs["cls_logits"])  # (B, A, K)
        boxes = boxes_lib.decode_boxes(
            anchors[None], outputs["box_deltas"], config.codec
        )
        boxes = boxes_lib.clip_boxes(boxes, image_hw)
        return nms(boxes, scores)

    return detect


def make_detect_fn(
    model,
    image_hw: tuple[int, int],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """Jitted (state, images (B,H,W,3)) → batched Detections for one bucket.

    With ``mesh``, the batch shards over the ``data`` axis and results gather
    back — eval uses every chip instead of the reference's rank-0-only path.
    """
    detect = _detect_body(model, image_hw, config)

    if mesh is None:
        return jax.jit(detect)

    sharded = shard_map(
        detect,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def compile_detect_fn(
    model,
    state,
    image_hw: tuple[int, int],
    batch_size: int,
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
    input_dtype: Any = None,
) -> Callable[[jnp.ndarray], nms_lib.Detections]:
    """AOT-lower + compile ONE bucket's detect program at a fixed batch
    size; returns ``call(images) -> Detections`` with ``state`` closed over.

    The shared load/dispatch path of the eval bench (bench.py --mode eval)
    and the serve engine (serve/engine.py): both need every
    (bucket, batch-size) executable built BEFORE traffic arrives, with the
    multi-second compile attributed by a trace span instead of hiding
    inside the first dispatch.  Inputs default to uint8 — the raw pipeline
    format; normalization runs inside the program (``_detect_body``).
    """
    fn = make_detect_fn(model, image_hw, config, mesh=mesh)
    spec = jax.ShapeDtypeStruct(
        (batch_size, *image_hw, 3),
        jnp.uint8 if input_dtype is None else input_dtype,
    )
    with trace.span(
        "aot_compile_detect",
        bucket=f"{image_hw[0]}x{image_hw[1]}",
        batch=batch_size,
    ):
        compiled = fn.lower(state, spec).compile()

    def call(images: jnp.ndarray) -> nms_lib.Detections:
        return compiled(state, images)

    return call


def make_detect_fn_spatial(
    model,
    image_hw: tuple[int, int],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """Detection with the IMAGE sharded across chips (spatial partitioning).

    The long-axis analogue of sequence/context parallelism for a CNN
    detector (SURVEY.md §2.4/§5.7): instead of sharding the batch, the
    image's H axis is sharded over the mesh and XLA GSPMD inserts halo
    exchanges for every conv — ring-attention's "pass the boundary"
    communication pattern, compiled automatically.  Useful when a single
    very large image (or tiny batch) must use many chips; per-image latency
    drops instead of throughput rising.

    Built with ``jit`` + sharding constraints rather than ``shard_map``:
    spatial conv partitioning needs the compiler's halo machinery, which
    manual per-device code would have to hand-roll.  Outputs are gathered
    (the anchor-major reshape reshards after the conv-heavy stage; NMS runs
    replicated, it is negligible next to the backbone).
    """
    from jax.sharding import NamedSharding

    if mesh is None:
        raise ValueError("spatial detection needs a mesh")
    rep = NamedSharding(mesh, P())
    img_sharding = NamedSharding(mesh, P(None, DATA_AXIS))  # shard H
    return jax.jit(
        _detect_body(model, image_hw, config),
        in_shardings=(rep, img_sharding),
        out_shardings=rep,
    )


def detections_to_coco(
    det: nms_lib.Detections,
    image_ids: np.ndarray,
    scales: np.ndarray,
    valid_rows: np.ndarray,
    label_to_cat_id: dict[int, int],
    image_sizes: dict[int, tuple[int, int]] | None = None,
) -> list[dict]:
    """Device Detections (one batch) → COCO result dicts in ORIGINAL coords.

    Boxes come back in resized-image coordinates; dividing by the per-image
    scale restores original coordinates (SURVEY.md M10 "rescale boxes").
    The device-side clip is to the static bucket extent (which includes
    padding), so with ``image_sizes`` ({image_id: (width, height)}) boxes are
    re-clamped to the true image bounds here; degenerate (zero-area) boxes —
    e.g. spurious hits entirely inside the padding — are dropped.
    """
    boxes = np.asarray(det.boxes, dtype=np.float64)
    scores = np.asarray(det.scores, dtype=np.float64)
    labels = np.asarray(det.labels)
    valid = np.asarray(det.valid)

    results: list[dict] = []
    for i in range(boxes.shape[0]):
        if not valid_rows[i]:
            continue  # eval padding row
        inv = 1.0 / float(scales[i])
        img_id = int(image_ids[i])
        wh = image_sizes.get(img_id) if image_sizes else None
        for j in np.flatnonzero(valid[i]):
            x1, y1, x2, y2 = boxes[i, j] * inv
            if wh is not None:
                x1, x2 = np.clip([x1, x2], 0.0, wh[0])
                y1, y2 = np.clip([y1, y2], 0.0, wh[1])
                if x2 <= x1 or y2 <= y1:
                    continue
            results.append(
                {
                    "image_id": img_id,
                    "category_id": int(label_to_cat_id[int(labels[i, j])]),
                    "bbox": [x1, y1, x2 - x1, y2 - y1],
                    "score": float(scores[i, j]),
                }
            )
    return results


def coco_gt_from_dataset(dataset: CocoDataset) -> tuple[list[dict], list[int]]:
    """Ground-truth annotation dicts + image-id list from a CocoDataset.

    Crowd annotations come through with ``iscrowd=1`` and per-annotation
    areas are preserved, so the oracle's ignore/area-range semantics match
    pycocotools on real COCO.  For full-fidelity eval construct the dataset
    with ``keep_empty=True`` (annotation-less images still collect FPs).
    """
    gts: list[dict] = []
    ann_id = 1
    for rec in dataset.records:
        for boxes, labels, areas, iscrowd in (
            (rec.boxes, rec.labels, rec.areas, 0),
            (rec.crowd_boxes, rec.crowd_labels, rec.crowd_areas, 1),
        ):
            for box, label, area in zip(boxes, labels, areas):
                x1, y1, x2, y2 = (float(v) for v in box)
                gts.append(
                    {
                        "id": ann_id,
                        "image_id": rec.image_id,
                        "category_id": dataset.label_to_cat_id[int(label)],
                        "bbox": [x1, y1, x2 - x1, y2 - y1],
                        "area": float(area),
                        "iscrowd": iscrowd,
                    }
                )
                ann_id += 1
    return gts, [rec.image_id for rec in dataset.records]


def _device_images(batch: Batch, mesh: Mesh | None):
    """Enqueue one eval batch's images host→device (sharded over ``mesh``).

    The eval twin of the train loop's ``_device_batch``: called from the
    prefetch thread so the DMA dispatch happens off the detect-dispatch
    path.  Process-local by design — multi-host eval runs on a LOCAL mesh
    over this process's shard of the val set (train.py's eval hook).
    """
    if mesh is None:
        return jax.device_put(batch.images)
    from jax.sharding import NamedSharding

    return jax.device_put(batch.images, NamedSharding(mesh, P(DATA_AXIS)))


class _EvalConsumer:
    """Stage-3 background consumer: device Detections → COCO result dicts
    (+ optional per-batch scoring hook), behind a bounded queue.

    Mirrors the shm pipeline's error contract
    (tests/unit/test_eval_pipeline.py):

    - a crash in the consumer (conversion or the scoring hook) re-raises
      in the DRIVER at its next ``put()``/``finish()`` — never a silent
      hang or a swallowed partial score;
    - ``close()`` stops the thread promptly even mid-queue (both ends are
      stop-gated) and is idempotent;
    - batches are consumed FIFO by one thread, so ``results`` is ordered
      exactly as the sequential path orders it (bit-identical output).
    """

    _DONE = object()

    def __init__(
        self,
        label_to_cat_id: dict[int, int],
        image_sizes: dict[int, tuple[int, int]] | None,
        on_batch: Callable[[list[dict], Sequence[int]], None] | None = None,
        maxsize: int = 4,
    ):
        self._label_to_cat_id = label_to_cat_id
        self._image_sizes = image_sizes
        self._on_batch = on_batch
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, maxsize))
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self.results: list[dict] = []
        # watchdog: registers in _run() at thread start.
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="eval-consumer"
        )
        self._thread.start()

    def _run(self) -> None:
        # Every poll iteration beats (the idle get(timeout) included — a
        # waiting consumer is healthy); only a WEDGED conversion/scoring
        # callback stops the heartbeat, which is exactly the previously
        # invisible failure the watchdog exists to name (ISSUE 3).
        hb = watchdog.register(
            "eval-consumer",
            details=lambda: {
                "qsize": self._queue.qsize(),
                "results": len(self.results),
            },
        )
        try:
            while not self._stop.is_set():
                hb.beat()
                try:
                    item = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                if item is self._DONE:
                    return
                det, image_ids, scales, valid = item
                with trace.span("eval_convert"):
                    batch_results = detections_to_coco(
                        det,
                        image_ids,
                        scales,
                        valid,
                        self._label_to_cat_id,
                        image_sizes=self._image_sizes,
                    )
                self.results.extend(batch_results)
                if self._on_batch is not None:
                    done = [
                        int(i) for i, v in zip(image_ids, valid) if v
                    ]
                    with trace.span("eval_score"):
                        self._on_batch(batch_results, done)
                if trace.enabled():
                    trace.counter("eval_consumer.qsize", self._queue.qsize())
        except BaseException as exc:  # re-raised in the driver
            self._error = exc
            self._stop.set()  # unblock a driver waiting on a full queue
        finally:
            hb.close()

    def _raise_pending(self) -> None:
        if self._error is not None:
            raise RuntimeError("eval consumer thread failed") from self._error

    def put(self, det, image_ids, scales, valid) -> None:
        """Hand one fetched batch to the consumer; raises its pending error."""
        self._raise_pending()
        if not pipeline_lib.stop_gated_put(
            self._queue, (det, image_ids, scales, valid), self._stop
        ):
            self._raise_pending()
            raise RuntimeError("eval consumer stopped")

    def finish(self) -> list[dict]:
        """Drain, join, surface any consumer error → ordered results."""
        pipeline_lib.stop_gated_put(self._queue, self._DONE, self._stop)
        self._thread.join()
        self._raise_pending()
        return self.results

    def close(self) -> None:
        """Abort without draining (driver unwinding on its own error)."""
        self._stop.set()
        self._thread.join(timeout=10)


def collect_detections(
    state,
    model,
    dataset: CocoDataset,
    batches: Iterable[Batch],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
    *,
    pipelined: bool = True,
    device_prefetch: int = 2,
    detect_fns: dict[tuple[int, int], Callable] | None = None,
    on_batch: Callable[[list[dict], Sequence[int]], None] | None = None,
) -> list[dict]:
    """Run detection over an eval batch stream → COCO result dicts.

    One detect function is compiled per shape bucket encountered (static
    shapes, SURVEY.md §7.3 hard part 1); the cache keys on (H, W).  Pass
    ``detect_fns`` to share compiled programs across calls (the eval bench
    times sequential vs pipelined on the same executables).

    ``pipelined`` selects the three-stage overlapped driver (module
    docstring); ``False`` is the strictly sequential reference path.  Both
    produce identical results in identical order
    (tests/unit/test_eval_pipeline.py pins bitwise equality).  ``on_batch``
    (if given) observes each batch's converted results plus the image ids
    it completed — in the consumer THREAD when pipelined, inline otherwise.
    """
    if detect_fns is None:
        detect_fns = {}
    image_sizes = {
        rec.image_id: (rec.width, rec.height) for rec in dataset.records
    }

    def fn_for(hw: tuple[int, int]) -> Callable:
        fn = detect_fns.get(hw)
        if fn is None:
            # AOT point: the jit wrapper is built here and compiles at its
            # first dispatch — mark it so a trace attributes the one-time
            # multi-second gap per bucket to compilation, not a stall.
            with trace.span("build_detect_fn", bucket=f"{hw[0]}x{hw[1]}"):
                fn = detect_fns[hw] = make_detect_fn(
                    model, hw, config, mesh=mesh
                )
        return fn

    if not pipelined:
        results: list[dict] = []
        for batch in batches:
            hw = batch.images.shape[1:3]
            det = jax.device_get(fn_for(hw)(state, jnp.asarray(batch.images)))
            batch_results = detections_to_coco(
                det,
                batch.image_ids,
                batch.scales,
                batch.valid,
                dataset.label_to_cat_id,
                image_sizes=image_sizes,
            )
            results.extend(batch_results)
            if on_batch is not None:
                on_batch(
                    batch_results,
                    [int(i) for i, v in zip(batch.image_ids, batch.valid) if v],
                )
        return results

    from batchai_retinanet_horovod_coco_tpu.data.prefetch import prefetch_map

    consumer = _EvalConsumer(
        dataset.label_to_cat_id, image_sizes, on_batch=on_batch
    )
    # Stage 1: host→device transfer runs in the prefetch thread, ``depth``
    # batches ahead of dispatch.  Shape/metadata stay host-side.
    staged = prefetch_map(
        batches,
        lambda b: (
            b.images.shape,
            _device_images(b, mesh),
            b.image_ids,
            b.scales,
            b.valid,
        ),
        depth=device_prefetch,
        thread_name="eval-device-prefetch",
    )
    # Stage 2: dispatch batch N, then pull batch N−1 (its program has
    # already finished or is ahead in the device stream): the device_get +
    # conversion of N−1 overlap N's forward+NMS on device.  The driver
    # carries its own heartbeat: the consumer beats on every idle poll and
    # the prefetch thread idles behind a full queue, so a wedge HERE —
    # device_get hanging on a dead device stream is the canonical one —
    # would otherwise be the only component with no liveness signal.
    hb = watchdog.register(
        "eval-driver", details=lambda: {"results": len(consumer.results)}
    )
    pending: tuple | None = None

    def fetch(det):
        with trace.span("detect_fetch"):
            fetched = jax.device_get(det)
        hb.beat()
        return fetched

    try:
        for shape, images_dev, image_ids, scales, valid in staged:
            hb.beat()
            with trace.span("detect_dispatch"):
                det = fn_for(shape[1:3])(state, images_dev)  # async dispatch
            if pending is not None:
                prev_det, prev_meta = pending
                fetched = fetch(prev_det)
                hb.idle()  # a full consumer queue is backpressure
                # Named span so the perf doctor can tell consumer
                # backpressure (slow host conversion/scoring) apart from
                # fetch blocking (slow device NMS) in the same driver.
                with trace.span("eval_put_wait"):
                    consumer.put(fetched, *prev_meta)
                hb.beat()
            pending = (det, (image_ids, scales, valid))
        if pending is not None:
            prev_det, prev_meta = pending
            pending = None
            fetched = fetch(prev_det)
            hb.idle()
            with trace.span("eval_put_wait"):
                consumer.put(fetched, *prev_meta)
        hb.idle()  # finish() legitimately blocks on the consumer's drain
        return consumer.finish()
    finally:
        staged.close()
        consumer.close()
        hb.close()


def allgather_process_detections(results: list[dict]) -> list[dict]:
    """Merge per-process detection shards across hosts.

    The sharded-eval gather: each process detects only ITS slice of the val
    set (the reference evaluated on rank 0 only — at pod scale that is
    hosts× redundant decode, SURVEY.md M10); the COCO result dicts pack into
    a fixed-width float64 array, pad to the max per-process count, and
    all-gather at the host level.  Every process returns the full merged
    list, so the subsequent scoring is identical everywhere (process 0
    logs).  Single-process: identity.
    """
    if jax.process_count() == 1:
        return results
    from jax.experimental import multihost_utils

    # Two packs: int64 ids would be canonicalized to int32 (and float64 to
    # float32) without jax_enable_x64, so 64-bit image ids (date-encoded COCO
    # ids are legal) travel as uint32 (lo, hi) halves; bbox/score are f32 on
    # device anyway, so the f32 pack loses nothing vs the unsharded path.
    n = len(results)
    ids = np.zeros((n, 3), np.uint32)  # image_id lo/hi, category_id
    vals = np.zeros((n, 5), np.float32)  # bbox xywh, score
    for i, r in enumerate(results):
        image_id = int(r["image_id"])
        ids[i] = [image_id & 0xFFFFFFFF, image_id >> 32, r["category_id"]]
        vals[i] = [*r["bbox"], r["score"]]
    counts = np.asarray(
        multihost_utils.process_allgather(np.uint32(n))
    ).reshape(-1)
    n_max = int(counts.max())
    if n_max == 0:
        return []
    ids_g = np.asarray(
        multihost_utils.process_allgather(
            np.pad(ids, ((0, n_max - n), (0, 0)))
        )
    )
    vals_g = np.asarray(
        multihost_utils.process_allgather(
            np.pad(vals, ((0, n_max - n), (0, 0)))
        )
    )
    merged: list[dict] = []
    for p in range(ids_g.shape[0]):
        for j in range(int(counts[p])):
            merged.append(
                {
                    "image_id": int(ids_g[p, j, 0]) | (int(ids_g[p, j, 1]) << 32),
                    "category_id": int(ids_g[p, j, 2]),
                    "bbox": [float(v) for v in vals_g[p, j, :4]],
                    "score": float(vals_g[p, j, 4]),
                }
            )
    return merged


def run_coco_eval(
    state,
    model,
    dataset: CocoDataset,
    batches: Iterable[Batch],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
    voc_metrics: bool = False,
    voc_weighted_average: bool = False,
    gather: bool = True,
    pipelined: bool = True,
    device_prefetch: int = 2,
    detect_fns: dict[tuple[int, int], Callable] | None = None,
) -> dict[str, float]:
    """Full eval pass: detect everything, then mAP via the numpy oracle.

    ``pipelined`` (default) runs the three-stage overlapped driver (module
    docstring): prefetch → one-behind async detect → background consumer.
    When the detections need no cross-process merge, the consumer
    additionally scores INCREMENTALLY (``StreamingCocoEval``), so the
    per-image COCO matching overlaps device NMS instead of running as a
    serial epilogue; metrics are identical either way
    (tests/unit/test_eval_pipeline.py).  ``pipelined=False`` is the
    strictly sequential reference path.

    With ``voc_metrics``, the same detection pass additionally yields
    PASCAL-VOC AP@0.5 per class (the reference's ``Evaluate`` callback
    metric for CSV/custom datasets, evaluate/voc_eval.py), merged into the
    returned dict under ``voc_*`` keys; ``voc_weighted_average`` weights
    the VOC mean by per-class annotation counts (the callback's flag).

    Multi-host: feed each process its shard of the val set (pipeline
    ``shard_index/shard_count``), detect on a LOCAL mesh, and the shards
    merge here via ``allgather_process_detections`` (``gather=False`` skips
    the merge for a deliberately process-local eval).
    """
    gt, img_ids = coco_gt_from_dataset(dataset)
    # Streaming scoring needs the full result set to BE this process's
    # result set: with a pending cross-process merge, score post-gather.
    scorer = None
    if pipelined and (not gather or jax.process_count() == 1):
        from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
            StreamingCocoEval,
        )

        scorer = StreamingCocoEval(
            gt, img_ids, cat_ids=list(dataset.label_to_cat_id.values())
        )
    dt = collect_detections(
        state,
        model,
        dataset,
        batches,
        config,
        mesh=mesh,
        pipelined=pipelined,
        device_prefetch=device_prefetch,
        detect_fns=detect_fns,
        on_batch=scorer.add if scorer is not None else None,
    )
    if gather:
        dt = allgather_process_detections(dt)
    if scorer is not None:
        metrics = scorer.finish()
    else:
        metrics = evaluate_detections(gt, dt, img_ids=img_ids)
    if voc_metrics:
        metrics.update(
            evaluate_detections_voc(
                gt, dt, weighted_average=voc_weighted_average
            )
        )
    return metrics
