"""On-device detection: forward → decode → clip → batched NMS, one XLA program.

Replaces the reference's separate "inference model" conversion step and its
``Anchors → RegressBoxes → ClipBoxes → FilterDetections`` layer stack
(SURVEY.md M3/M6, call stack 3.5, ``bin/convert_model.py``): here inference
is just another jitted function over the same train-state params, with the
whole post-processing (sigmoid, top-k pre-select, class-masked NMS) running
on the TPU per BASELINE.json configs[4] ("on-device batched NMS").

``run_coco_eval`` is the dataset-level driver (the ``CocoEval`` callback /
``evaluate_coco()`` equivalent, SURVEY.md M10): stream the eval pipeline,
detect per static shape bucket (one compiled program each), rescale boxes to
original image coordinates on host, and hand COCO-format results to the
numpy mAP oracle (evaluate/coco_eval.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu.data import pipeline as pipeline_lib
from batchai_retinanet_horovod_coco_tpu.data.coco import CocoDataset
from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import evaluate_detections
from batchai_retinanet_horovod_coco_tpu.evaluate.voc_eval import (
    evaluate_detections_voc,
)
from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
from batchai_retinanet_horovod_coco_tpu.ops import boxes as boxes_lib
from batchai_retinanet_horovod_coco_tpu.ops import nms as nms_lib
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.train.state import model_variables


@dataclasses.dataclass(frozen=True)
class DetectConfig:
    """FilterDetections-equivalent knobs (reference defaults, SURVEY.md M6)."""

    score_threshold: float = 0.05
    iou_threshold: float = 0.5
    pre_nms_size: int = 1000
    max_detections: int = 300
    codec: boxes_lib.BoxCodecConfig = boxes_lib.BoxCodecConfig()
    anchor: anchors_lib.AnchorConfig = anchors_lib.AnchorConfig()


def _detect_body(
    model, image_hw: tuple[int, int], config: DetectConfig
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """The ONE detection pipeline every factory wraps: normalize → forward →
    sigmoid → decode → clip → batched NMS.  Shared so the batch-sharded and
    spatially-sharded paths can never drift from the single-device one."""
    anchors = jnp.asarray(
        anchors_lib.anchors_for_image_shape(image_hw, config.anchor)
    )

    def detect(state, images: jnp.ndarray) -> nms_lib.Detections:
        # uint8 batches normalize on device (data/pipeline.normalize_images).
        images = pipeline_lib.normalize_images(images)
        outputs = model.apply(model_variables(state), images, train=False)
        scores = jax.nn.sigmoid(outputs["cls_logits"])  # (B, A, K)
        boxes = boxes_lib.decode_boxes(
            anchors[None], outputs["box_deltas"], config.codec
        )
        boxes = boxes_lib.clip_boxes(boxes, image_hw)
        return nms_lib.batched_multiclass_nms(
            boxes,
            scores,
            score_threshold=config.score_threshold,
            iou_threshold=config.iou_threshold,
            pre_nms_size=config.pre_nms_size,
            max_detections=config.max_detections,
        )

    return detect


def make_detect_fn(
    model,
    image_hw: tuple[int, int],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """Jitted (state, images (B,H,W,3)) → batched Detections for one bucket.

    With ``mesh``, the batch shards over the ``data`` axis and results gather
    back — eval uses every chip instead of the reference's rank-0-only path.
    """
    detect = _detect_body(model, image_hw, config)

    if mesh is None:
        return jax.jit(detect)

    sharded = shard_map(
        detect,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_detect_fn_spatial(
    model,
    image_hw: tuple[int, int],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
) -> Callable[[Any, jnp.ndarray], nms_lib.Detections]:
    """Detection with the IMAGE sharded across chips (spatial partitioning).

    The long-axis analogue of sequence/context parallelism for a CNN
    detector (SURVEY.md §2.4/§5.7): instead of sharding the batch, the
    image's H axis is sharded over the mesh and XLA GSPMD inserts halo
    exchanges for every conv — ring-attention's "pass the boundary"
    communication pattern, compiled automatically.  Useful when a single
    very large image (or tiny batch) must use many chips; per-image latency
    drops instead of throughput rising.

    Built with ``jit`` + sharding constraints rather than ``shard_map``:
    spatial conv partitioning needs the compiler's halo machinery, which
    manual per-device code would have to hand-roll.  Outputs are gathered
    (the anchor-major reshape reshards after the conv-heavy stage; NMS runs
    replicated, it is negligible next to the backbone).
    """
    from jax.sharding import NamedSharding

    if mesh is None:
        raise ValueError("spatial detection needs a mesh")
    rep = NamedSharding(mesh, P())
    img_sharding = NamedSharding(mesh, P(None, DATA_AXIS))  # shard H
    return jax.jit(
        _detect_body(model, image_hw, config),
        in_shardings=(rep, img_sharding),
        out_shardings=rep,
    )


def detections_to_coco(
    det: nms_lib.Detections,
    image_ids: np.ndarray,
    scales: np.ndarray,
    valid_rows: np.ndarray,
    label_to_cat_id: dict[int, int],
    image_sizes: dict[int, tuple[int, int]] | None = None,
) -> list[dict]:
    """Device Detections (one batch) → COCO result dicts in ORIGINAL coords.

    Boxes come back in resized-image coordinates; dividing by the per-image
    scale restores original coordinates (SURVEY.md M10 "rescale boxes").
    The device-side clip is to the static bucket extent (which includes
    padding), so with ``image_sizes`` ({image_id: (width, height)}) boxes are
    re-clamped to the true image bounds here; degenerate (zero-area) boxes —
    e.g. spurious hits entirely inside the padding — are dropped.
    """
    boxes = np.asarray(det.boxes, dtype=np.float64)
    scores = np.asarray(det.scores, dtype=np.float64)
    labels = np.asarray(det.labels)
    valid = np.asarray(det.valid)

    results: list[dict] = []
    for i in range(boxes.shape[0]):
        if not valid_rows[i]:
            continue  # eval padding row
        inv = 1.0 / float(scales[i])
        img_id = int(image_ids[i])
        wh = image_sizes.get(img_id) if image_sizes else None
        for j in np.flatnonzero(valid[i]):
            x1, y1, x2, y2 = boxes[i, j] * inv
            if wh is not None:
                x1, x2 = np.clip([x1, x2], 0.0, wh[0])
                y1, y2 = np.clip([y1, y2], 0.0, wh[1])
                if x2 <= x1 or y2 <= y1:
                    continue
            results.append(
                {
                    "image_id": img_id,
                    "category_id": int(label_to_cat_id[int(labels[i, j])]),
                    "bbox": [x1, y1, x2 - x1, y2 - y1],
                    "score": float(scores[i, j]),
                }
            )
    return results


def coco_gt_from_dataset(dataset: CocoDataset) -> tuple[list[dict], list[int]]:
    """Ground-truth annotation dicts + image-id list from a CocoDataset.

    Crowd annotations come through with ``iscrowd=1`` and per-annotation
    areas are preserved, so the oracle's ignore/area-range semantics match
    pycocotools on real COCO.  For full-fidelity eval construct the dataset
    with ``keep_empty=True`` (annotation-less images still collect FPs).
    """
    gts: list[dict] = []
    ann_id = 1
    for rec in dataset.records:
        for boxes, labels, areas, iscrowd in (
            (rec.boxes, rec.labels, rec.areas, 0),
            (rec.crowd_boxes, rec.crowd_labels, rec.crowd_areas, 1),
        ):
            for box, label, area in zip(boxes, labels, areas):
                x1, y1, x2, y2 = (float(v) for v in box)
                gts.append(
                    {
                        "id": ann_id,
                        "image_id": rec.image_id,
                        "category_id": dataset.label_to_cat_id[int(label)],
                        "bbox": [x1, y1, x2 - x1, y2 - y1],
                        "area": float(area),
                        "iscrowd": iscrowd,
                    }
                )
                ann_id += 1
    return gts, [rec.image_id for rec in dataset.records]


def collect_detections(
    state,
    model,
    dataset: CocoDataset,
    batches: Iterable[Batch],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
) -> list[dict]:
    """Run detection over an eval batch stream → COCO result dicts.

    One detect function is compiled per shape bucket encountered (static
    shapes, SURVEY.md §7.3 hard part 1); the cache keys on (H, W).
    """
    detect_fns: dict[tuple[int, int], Callable] = {}
    image_sizes = {
        rec.image_id: (rec.width, rec.height) for rec in dataset.records
    }
    results: list[dict] = []
    for batch in batches:
        hw = batch.images.shape[1:3]
        fn = detect_fns.get(hw)
        if fn is None:
            fn = detect_fns[hw] = make_detect_fn(model, hw, config, mesh=mesh)
        det = jax.device_get(fn(state, jnp.asarray(batch.images)))
        results.extend(
            detections_to_coco(
                det,
                batch.image_ids,
                batch.scales,
                batch.valid,
                dataset.label_to_cat_id,
                image_sizes=image_sizes,
            )
        )
    return results


def allgather_process_detections(results: list[dict]) -> list[dict]:
    """Merge per-process detection shards across hosts.

    The sharded-eval gather: each process detects only ITS slice of the val
    set (the reference evaluated on rank 0 only — at pod scale that is
    hosts× redundant decode, SURVEY.md M10); the COCO result dicts pack into
    a fixed-width float64 array, pad to the max per-process count, and
    all-gather at the host level.  Every process returns the full merged
    list, so the subsequent scoring is identical everywhere (process 0
    logs).  Single-process: identity.
    """
    if jax.process_count() == 1:
        return results
    from jax.experimental import multihost_utils

    # Two packs: int64 ids would be canonicalized to int32 (and float64 to
    # float32) without jax_enable_x64, so 64-bit image ids (date-encoded COCO
    # ids are legal) travel as uint32 (lo, hi) halves; bbox/score are f32 on
    # device anyway, so the f32 pack loses nothing vs the unsharded path.
    n = len(results)
    ids = np.zeros((n, 3), np.uint32)  # image_id lo/hi, category_id
    vals = np.zeros((n, 5), np.float32)  # bbox xywh, score
    for i, r in enumerate(results):
        image_id = int(r["image_id"])
        ids[i] = [image_id & 0xFFFFFFFF, image_id >> 32, r["category_id"]]
        vals[i] = [*r["bbox"], r["score"]]
    counts = np.asarray(
        multihost_utils.process_allgather(np.uint32(n))
    ).reshape(-1)
    n_max = int(counts.max())
    if n_max == 0:
        return []
    ids_g = np.asarray(
        multihost_utils.process_allgather(
            np.pad(ids, ((0, n_max - n), (0, 0)))
        )
    )
    vals_g = np.asarray(
        multihost_utils.process_allgather(
            np.pad(vals, ((0, n_max - n), (0, 0)))
        )
    )
    merged: list[dict] = []
    for p in range(ids_g.shape[0]):
        for j in range(int(counts[p])):
            merged.append(
                {
                    "image_id": int(ids_g[p, j, 0]) | (int(ids_g[p, j, 1]) << 32),
                    "category_id": int(ids_g[p, j, 2]),
                    "bbox": [float(v) for v in vals_g[p, j, :4]],
                    "score": float(vals_g[p, j, 4]),
                }
            )
    return merged


def run_coco_eval(
    state,
    model,
    dataset: CocoDataset,
    batches: Iterable[Batch],
    config: DetectConfig = DetectConfig(),
    mesh: Mesh | None = None,
    voc_metrics: bool = False,
    voc_weighted_average: bool = False,
    gather: bool = True,
) -> dict[str, float]:
    """Full eval pass: detect everything, then mAP via the numpy oracle.

    With ``voc_metrics``, the same detection pass additionally yields
    PASCAL-VOC AP@0.5 per class (the reference's ``Evaluate`` callback
    metric for CSV/custom datasets, evaluate/voc_eval.py), merged into the
    returned dict under ``voc_*`` keys; ``voc_weighted_average`` weights
    the VOC mean by per-class annotation counts (the callback's flag).

    Multi-host: feed each process its shard of the val set (pipeline
    ``shard_index/shard_count``), detect on a LOCAL mesh, and the shards
    merge here via ``allgather_process_detections`` (``gather=False`` skips
    the merge for a deliberately process-local eval).
    """
    dt = collect_detections(state, model, dataset, batches, config, mesh=mesh)
    if gather:
        dt = allgather_process_detections(dt)
    gt, img_ids = coco_gt_from_dataset(dataset)
    metrics = evaluate_detections(gt, dt, img_ids=img_ids)
    if voc_metrics:
        metrics.update(
            evaluate_detections_voc(
                gt, dt, weighted_average=voc_weighted_average
            )
        )
    return metrics
