"""ZeRO-style weight-update sharding over the ``data`` mesh axis.

The reference replicates optimizer state on every GPU and applies the same
update N times (Horovod's model, SURVEY.md §2.4).  This optional mode shards
the *weight update* instead — the cross-replica weight-update sharding of
PAPERS.md "Automatic Cross-Replica Sharding of Weight Update" and the ZeRO
optimizer-state partitioning idea:

- gradients leave the backward pass via ``psum_scatter`` (reduce-scatter):
  each device receives the 1/N shard of the summed gradient it owns —
  half the collective bytes of the plain ``pmean`` all-reduce;
- each device stores ONLY its 1/N shard of the optimizer state (momentum /
  Adam moments: the dominant state memory) and updates its 1/N of the
  parameters;
- updated parameter shards return to full replication via a tiled
  ``all_gather`` (reduce_scatter + all_gather == all_reduce, so the total
  collective traffic matches the baseline while state memory and update
  compute drop by N).

Storage layout: every parameter leaf is flattened, zero-padded to a multiple
of N, and its optimizer-state counterparts live as global ``(N * chunk,)``
arrays sharded on the leading axis.  Scalar state (schedule counts, plateau
controllers) stays replicated.

The pytree STRUCTURE of a sharded opt_state is identical to the replicated
one (``tx.init`` over a params-like tree of shards), only the leaf shapes
differ — and because the padding is zeros, converting between world sizes
(or to/from the replicated layout) is pure shape surgery:
``reshard_flat_leaf`` below truncates-or-zero-pads the flat representation
to the target layout, refusing loudly if the truncated tail carries data.
That is what makes checkpoints world-size-elastic (ISSUE 11,
utils/checkpoint.py): a ZeRO checkpoint saved at world N restores at world
M ≠ N — including M = 1, the replicated single-host recovery of a pod
snapshot — which the reference could not do (Horovod checkpoints assumed
the same world size for optimizer slots), and which the weight-update
sharding paper (PAPERS.md) treats as the resharding problem.

Gradient clipping: ``optax.clip_by_global_norm`` inside the chain would see
only the local shard and compute a wrong norm, so the chain is built without
it (train/optim.py ``include_clip=False``) and the step applies the same
``scale = clip / max(norm, clip)`` rule from the psum of per-shard square
sums — bitwise-equivalent semantics, global by construction.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS


def _chunk(size: int, n: int) -> int:
    return -(-size // n)


def _pad_flat(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Flatten and zero-pad to ``n * chunk`` elements."""
    flat = x.reshape(-1)
    pad = n * _chunk(flat.size, n) - flat.size
    return jnp.pad(flat, (0, pad)) if pad else flat


def _local_shard(x: jnp.ndarray, n: int, index: jnp.ndarray) -> jnp.ndarray:
    """This device's ``(chunk,)`` slice of a padded-flat parameter."""
    flat = _pad_flat(x, n)
    chunk = flat.size // n
    return lax.dynamic_slice(flat, (index * chunk,), (chunk,))


def _unshard(shard: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """All-gather shards back into the original leaf shape."""
    full = lax.all_gather(shard, DATA_AXIS, tiled=True)
    return full[: like.size].reshape(like.shape)


def shard_template(params: Any, n: int) -> Any:
    """Per-device parameter-shard ShapeDtypeStructs (tx.init template)."""
    return jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((_chunk(p.size, n),), p.dtype), params
    )


def opt_state_partition_specs(opt_state: Any) -> Any:
    """PartitionSpec tree for a sharded opt_state (THE storage-format rule).

    Rule: state leaves derived from parameters are 1-D ``(chunk,)`` per
    device → sharded on the leading axis; scalar leaves (counts, plateau
    controllers) are replicated.  Every optax transform used by
    train/optim.py fits this shape dichotomy by construction.  This is the
    single owner of the rule — the train step's shard_map specs and the
    loop's post-restore placement both derive from here.
    """
    return jax.tree.map(
        lambda l: P(DATA_AXIS) if getattr(l, "ndim", 0) >= 1 else P(),
        opt_state,
    )


def opt_state_specs(tx: optax.GradientTransformation, params: Any, n: int) -> Any:
    """PartitionSpec tree for the sharded opt_state of ``tx`` over ``params``."""
    return opt_state_partition_specs(
        jax.eval_shape(tx.init, shard_template(params, n))
    )


def clip_by_global_norm_sharded(
    max_norm: float,
    axis_name: str = DATA_AXIS,
    use_precomputed: bool = True,
) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` for updates living as 1/N shards.

    The in-chain optax clip would compute the norm of the LOCAL shard only;
    this transform psums the per-shard square sums over ``axis_name`` (the
    shards partition the full gradient exactly; padding contributes zeros),
    so the clip decision is global.  Because it sits INSIDE the optax chain,
    ``optax.multi_transform`` masking (--freeze-backbone) applies to it
    exactly as to the replicated clip: frozen leaves never enter the norm.
    Must run inside ``shard_map`` (uses a named-axis collective).
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None, *, grad_norm=None, **extra):
        del params, extra
        if grad_norm is None or not use_precomputed:
            # Self-computed: psum the per-shard square sums (the shards
            # partition the full gradient exactly; padding is zeros).
            # ``use_precomputed=False`` FORCES this — a freeze-masked
            # chain sees only its subtree, whose norm differs from the
            # step's full-tree value (train/optim.py).
            sq = sum(
                jnp.sum(jnp.square(g)) for g in jax.tree.leaves(updates)
            )
            norm = jnp.sqrt(lax.psum(sq, axis_name))
        else:
            # sharded_update already psum-ed this exact norm for its
            # grad_norm metric (ISSUE 10: the pre-clip norm is computed
            # once and shared, never recomputed).
            norm = grad_norm
        scale = max_norm / jnp.maximum(norm, max_norm)
        return jax.tree.map(lambda g: g * scale, updates), state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def reshard_flat_leaf(saved, shape, dtype, path: str = ""):
    """Re-lay one optimizer-state leaf saved in one ZeRO/replicated layout
    into another (host-side numpy; the checkpoint restore path).

    The storage-format rule (``opt_state_partition_specs``) means a leaf is
    either its logical parameter shape (replicated layout) or a flat
    zero-padded ``(N * chunk,)`` array (world-N sharded layout), and the
    padded flat form CONTAINS the logical content as a prefix with zeros
    after it.  So any layout→layout conversion is: flatten, truncate or
    zero-pad to the target element count, reshape — valid iff every
    truncated element is zero (anything else means the checkpoint does not
    actually hold this parameter's state: wrong model, wrong optimizer, or
    corruption — refuse loudly rather than silently drop data).
    """
    import numpy as np

    saved = np.asarray(saved)
    shape = tuple(int(d) for d in shape)
    if saved.dtype != np.dtype(dtype):
        raise ValueError(
            f"checkpoint leaf {path or '<leaf>'}: dtype "
            f"{saved.dtype} != expected {np.dtype(dtype)}"
        )
    if saved.shape == shape:
        return saved
    if saved.ndim != 1 and len(shape) != 1:
        # Neither side is a flat ZeRO layout — this is a genuine model/
        # optimizer mismatch, not a resharding problem.
        raise ValueError(
            f"checkpoint leaf {path or '<leaf>'}: shape {saved.shape} != "
            f"expected {shape} and neither is a flat ZeRO layout"
        )
    flat = saved.reshape(-1)
    target = 1
    for d in shape:
        target *= d
    if flat.size > target:
        if np.count_nonzero(flat[target:]):
            raise ValueError(
                f"checkpoint leaf {path or '<leaf>'}: truncating "
                f"{flat.size} -> {target} elements would drop non-zero "
                "state (not ZeRO padding) — the checkpoint does not match "
                "this model/optimizer"
            )
        flat = flat[:target]
    elif flat.size < target:
        flat = np.pad(flat, (0, target - flat.size))
    return np.ascontiguousarray(flat.reshape(shape))


def init_sharded_opt_state(
    tx: optax.GradientTransformation, params: Any, mesh: Mesh
) -> Any:
    """Build the global sharded opt_state for ``params`` on ``mesh``.

    Each device initializes the transform on its own parameter shard; the
    result is the global pytree whose sharded leaves are ``(N * chunk,)``
    arrays laid out along the ``data`` axis.
    """
    n = mesh.size
    specs = opt_state_specs(tx, params, n)

    @partial(
        shard_map, mesh=mesh, in_specs=(P(),), out_specs=specs, check_vma=False
    )
    def init(p):
        index = lax.axis_index(DATA_AXIS)
        shards = jax.tree.map(lambda x: _local_shard(x, n, index), p)
        return tx.init(shards)

    return jax.jit(init)(params)


def sharded_update(
    tx: optax.GradientTransformation,
    grads: Any,
    opt_state: Any,
    params: Any,
    *,
    n: int,
    loss_value: jnp.ndarray | None = None,
    gather_updates=None,
) -> tuple[Any, Any, dict[str, jnp.ndarray]]:
    """One weight update on this device's shard; call INSIDE shard_map.

    ``grads`` are the local per-device gradients (pre-allreduce); the
    reduce-scatter happens here.  Gradient clipping is ``tx``'s concern:
    build the chain with ``clip_by_global_norm_sharded`` (train/optim.py
    ``shard_clip_axis``) so the norm is global across shards.  Returns
    (new_params FULL via all_gather, new_opt_state local shards,
    info dict with the pre-clip ``grad_norm`` — SURVEY.md §5.5 metric).

    ``gather_updates(updates, params) -> new_params`` (optional, ISSUE
    13): replaces the f32 param all-gather with a caller-owned
    collective over the optax UPDATE shards — the comm subsystem's
    compressed update gather (``comm/compress.zero_gather_updates``),
    which is what makes ZeRO + compression composable (gathering the
    gradient-like update with error feedback instead of quantizing the
    params themselves).  The gradient reduce-scatter, the sharded
    optimizer update, and the global clip norm are UNCHANGED either way.
    """
    index = lax.axis_index(DATA_AXIS)
    gshards = jax.tree.map(
        lambda g: lax.psum_scatter(_pad_flat(g, n), DATA_AXIS, tiled=True) / n,
        grads,
    )
    # The shards partition the mean gradient exactly (padding is zeros), so
    # the global norm is the psum of per-shard square sums.
    sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree.leaves(gshards))
    info = {"grad_norm": jnp.sqrt(lax.psum(sq, DATA_AXIS))}
    pshards = jax.tree.map(lambda p: _local_shard(p, n, index), params)
    if isinstance(tx, optax.GradientTransformationExtraArgs):
        # Forward the already-psum-ed pre-clip norm so the in-chain
        # sharded clip reuses it instead of a second psum; value= feeds
        # reduce_on_plateau when the schedule carries one.
        extra = {"grad_norm": info["grad_norm"]}
        if loss_value is not None:
            extra["value"] = loss_value
        updates, new_opt_state = tx.update(
            gshards, opt_state, pshards, **extra
        )
    else:
        updates, new_opt_state = tx.update(gshards, opt_state, pshards)
    if gather_updates is not None:
        # Compressed path: every device applies the identical
        # dequantized full update to its replicated params, so the
        # params stay bitwise replicated without an f32 gather.
        return gather_updates(updates, params), new_opt_state, info
    new_pshards = optax.apply_updates(pshards, updates)
    new_params = jax.tree.map(_unshard, new_pshards, params)
    return new_params, new_opt_state, info
