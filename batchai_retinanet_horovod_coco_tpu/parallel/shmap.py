"""One import site for ``shard_map`` across jax versions.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace (and renamed its replication-check kwarg ``check_rep`` →
``check_vma``) across releases.  Importing it from ``jax`` directly made the
whole SPMD layer (train step, ZeRO, sharded detect) fail to import on the
older runtime, taking 17 tier-1 test modules down with it.  Every module
imports the symbol from here instead; callers always write ``check_vma=``
and the shim translates for the runtime it finds.
"""

from __future__ import annotations

try:  # newer jax: top-level export, kwarg is check_vma
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, kwarg is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f=None, *, check_vma=None, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg normalized.

    Usable exactly like the real thing: directly (``shard_map(fn, mesh=...,
    ...)``) or via ``functools.partial`` as a decorator.
    """
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    if f is None:
        return lambda fn: _shard_map(fn, **kwargs)
    return _shard_map(f, **kwargs)
