"""Quantized gradient all-reduce: trade gradient precision for ICI bandwidth.

SURVEY.md §5.8 names EQuARX-style quantized all-reduce (PAPERS.md) as the
optional bandwidth optimization over the plain compiled ``pmean``.  True
in-ring requantization is not expressible with XLA's collectives, so this is
the two-phase decomposition with the compression on the phase that can take
it:

  1. ``psum_scatter`` in f32 — each device ends up owning the fully-reduced
     1/N shard of every gradient (wire cost (N-1)/N · 4S bytes, same as the
     first half of a ring all-reduce; summation precision is untouched);
  2. per-BLOCK int8 quantization (symmetric, max/127 scale per
     ``_QUANT_BLOCK``-element block, EQuARX-style) and an int8
     ``all_gather`` of shards + f32 block scales (wire cost (N-1)/N · S
     bytes + one f32 per block — <1% overhead at block 512 — vs · 4S for
     the f32 gather half).

Total wire traffic ≈ 5/8 of the plain all-reduce.  Every device dequantizes
the same gathered bytes, so the replicated update stays bitwise-identical
across devices; the only error is one symmetric rounding of the ALREADY
REDUCED gradient, bounded per element by max|block| / 254 — tighter than
quantize-before-reduce schemes, whose error compounds over N summands.
Block-local scales matter because gradients are heavy-tailed: with one
scale per multi-million-element shard, a single outlier zeroes every
element below max|shard|/254 (100% relative error for small-magnitude
entries); a 512-element block bounds an outlier's blast radius to its own
block (ADVICE r2).
Opt-in via ``--quantized-allreduce`` (train/step.py); gradient clipping and
the optimizer run on the dequantized values unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from batchai_retinanet_horovod_coco_tpu.parallel.zero import _pad_flat

_MIN_QUANTIZE_SIZE = 8192  # below this the wire saving is noise; stay exact
_QUANT_BLOCK = 512  # elements per int8 scale (EQuARX-style block scaling)


def _quantized_pmean_flat(flat: jnp.ndarray, axis_name: str, n: int) -> jnp.ndarray:
    """pmean of a flat f32 vector via reduce-scatter + int8 all-gather."""
    size = flat.shape[0]
    flat = _pad_flat(flat, n)  # shared pad-to-shardable rule (zero.py)
    # Phase 1: exact f32 reduction; each device owns one reduced shard.
    shard = lax.psum_scatter(flat, axis_name, tiled=True) / n
    # Phase 2: symmetric int8 with per-block scales (gathered alongside);
    # block-local scaling keeps an outlier from zeroing the whole shard.
    m = shard.shape[0]
    blocks = -(-m // _QUANT_BLOCK)
    sb = jnp.pad(shard, (0, blocks * _QUANT_BLOCK - m)).reshape(
        blocks, _QUANT_BLOCK
    )
    amax = jnp.max(jnp.abs(sb), axis=1)  # (blocks,)
    # A non-finite gradient must SURFACE (the loop's non-finite-loss abort,
    # SURVEY §5.2) — int8 casting would launder Inf/NaN into finite garbage,
    # so poison that block's gathered scale instead: its dequantized values
    # go NaN and the divergence aborts exactly like the exact-pmean path.
    scale = jnp.where(
        jnp.isfinite(amax), jnp.maximum(amax, 1e-30) / 127.0, jnp.nan
    )
    q = jnp.clip(jnp.round(sb / scale[:, None]), -127.0, 127.0).astype(jnp.int8)
    q_all = lax.all_gather(q, axis_name)  # (n, blocks, _QUANT_BLOCK) int8
    s_all = lax.all_gather(scale, axis_name)  # (n, blocks) f32
    out = (
        (q_all.astype(jnp.float32) * s_all[..., None])
        .reshape(n, blocks * _QUANT_BLOCK)[:, :m]
        .reshape(-1)
    )
    return out[:size]


def quantized_pmean(grads, axis_name: str, n: int):
    """``lax.pmean`` over ``axis_name`` with int8-compressed gather phase.

    Leaves smaller than ``_MIN_QUANTIZE_SIZE`` elements (biases, norm
    scales — a rounding there is all pain, no bandwidth) and non-float
    leaves take the exact ``pmean``.
    """

    def one(g):
        if g.size < _MIN_QUANTIZE_SIZE or not jnp.issubdtype(
            g.dtype, jnp.floating
        ):
            return lax.pmean(g, axis_name)
        return (
            _quantized_pmean_flat(
                g.astype(jnp.float32).reshape(-1), axis_name, n
            )
            .reshape(g.shape)
            .astype(g.dtype)
        )

    return jax.tree.map(one, grads)
