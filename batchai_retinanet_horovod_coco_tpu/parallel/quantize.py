"""DEPRECATED shim over the comm subsystem (ISSUE 13).

This module used to hold the per-leaf int8-gather pmean (SURVEY.md §5.8's
EQuARX-style option).  That implementation had a structural blind spot:
leaves below ``_MIN_QUANTIZE_SIZE`` were skipped PER LEAF — every bias
and norm scale paid exact bytes AND its own collective — and there was
no error feedback, so the rounding bias compounded step over step.

``comm/compress.py`` subsumes it: leaves pack into per-stage buckets
(small leaves ride inside full buckets; only a bucket whose total
payload is under ``CommConfig.min_bucket_bytes`` — the successor of the
old per-leaf constant — stays exact), the reduce keeps the exact-f32
two-phase decomposition, and error feedback carries the dropped
rounding in ``TrainState.comm_state``.

``quantized_pmean`` remains as a thin stateless alias so old call sites
(``make_train_step(quantized_allreduce=True)``, the 2-process pod
worker's "quantized" flavor) keep working; new code should build a
``comm.CommConfig`` instead.
"""

from __future__ import annotations

from batchai_retinanet_horovod_coco_tpu.comm.compress import (
    bucketed_pmean,
)


def quantized_pmean(grads, axis_name: str, n: int):
    """DEPRECATED: stateless bucketed int8 pmean (no error feedback).

    Alias for ``comm.compress.bucketed_pmean`` with the default int8
    policy — same exact-reduce-then-quantize error bound as the old
    per-leaf path (one symmetric rounding of the ALREADY REDUCED
    gradient, ≤ max|block| / 254 per element), minus the per-leaf
    small-tensor blind spot.
    """
    return bucketed_pmean(grads, axis_name, n)
