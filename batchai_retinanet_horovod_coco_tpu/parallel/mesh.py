"""Device mesh construction and canonical shardings.

The flagship parallelism strategy is pure data-parallel over a 1-D ``data``
axis (SURVEY.md §2.4: DP is the only strategy the reference uses; TP/PP/SP
are deliberately not built for RetinaNet-R50, which fits per chip).  The mesh
abstraction still goes through ``jax.sharding.Mesh`` so that wider meshes
(e.g. a future ``spatial`` axis for XLA SPMD partitioning of very large
images) slot in without touching call sites.

Multi-host: ``jax.devices()`` returns the GLOBAL device list after
``jax.distributed.initialize`` (launch/pod.py), so the same mesh code serves
1 chip, one host with 8 chips, and a v5e-256 pod slice unchanged.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
# Second mesh axis for spatial (image-H) partitioning — the training-side
# sequence/context-parallel analogue (train.step.make_train_step_spatial,
# evaluate.detect.make_detect_fn_spatial).
SPACE_AXIS = "space"

#: Env override for the slice count (same knob as ``--comm-slices``):
#: lets the 8-device virtual CPU mesh emulate e.g. 2 slices x 4 devices.
COMM_SLICES_ENV = "RETINANET_COMM_SLICES"


@dataclasses.dataclass(frozen=True)
class CommTopology:
    """Two-level device grouping for hierarchical collectives (ISSUE 16).

    A pod is two fabrics: fast ICI within a slice, slow DCN across
    slices.  ``num_slices`` (S) counts slices, ``slice_size`` (L) the
    devices per slice; the mesh's 1-D data axis holds ``S * L`` devices.

    Mesh-position convention — INTERLEAVED: position ``d`` on the data
    axis belongs to slice ``d % S`` with intra-slice rank ``d // S``.
    This is deliberate, not cosmetic: after the hierarchical tree's two
    reduce-scatters (ICI tile by rank, then DCN tile by slice), the
    final shard of position ``d`` covers global flat elements
    ``[d * chunk, (d + 1) * chunk)`` — so the per-hop EF residual
    arrays, sharded ``P(DATA_AXIS)`` in position order, stay in GLOBAL
    BUCKET ORDER (logical prefix + zero padding), which is exactly the
    invariant ``parallel.zero.reshard_flat_leaf`` needs for checkpoint
    elasticity across world-size changes.  ``arrange_devices`` orders
    real slice-indexed devices to match.
    """

    num_slices: int
    slice_size: int

    def __post_init__(self):
        if self.num_slices < 1 or self.slice_size < 1:
            raise ValueError(
                f"CommTopology needs num_slices >= 1 and slice_size >= 1, "
                f"got {self.num_slices} x {self.slice_size}"
            )

    @property
    def num_devices(self) -> int:
        return self.num_slices * self.slice_size

    def ici_groups(self) -> list:
        """Mesh positions grouped by slice (the fast-fabric groups):
        group ``s`` lists slice ``s``'s members in intra-slice rank
        order — the order grouped ``psum_scatter`` tiles by."""
        S, L = self.num_slices, self.slice_size
        return [[r * S + s for r in range(L)] for s in range(S)]

    def dcn_groups(self) -> list:
        """Mesh positions grouped by intra-slice rank (the slow-fabric
        groups): group ``r`` lists rank ``r``'s device on every slice,
        in slice order."""
        S, L = self.num_slices, self.slice_size
        return [[r * S + s for s in range(S)] for r in range(L)]


def derive_topology(
    num_devices: int, num_slices: int | None = None
) -> CommTopology | None:
    """CommTopology for a ``num_devices``-wide data axis, or None (flat).

    Slice count resolution, highest priority first: the explicit
    ``num_slices`` argument (the ``--comm-slices`` CLI knob), the
    ``RETINANET_COMM_SLICES`` env var, then the devices' own
    ``slice_index`` attribute (real multi-slice TPU).  CPU/GPU devices
    carry no slice_index, so the virtual mesh is flat unless the
    override says otherwise — that override is how the 8-device CPU
    mesh emulates 2 slices x 4 devices."""
    if num_slices is None:
        env = os.environ.get(COMM_SLICES_ENV, "").strip()
        if env:
            try:
                num_slices = int(env)
            except ValueError:
                raise ValueError(
                    f"{COMM_SLICES_ENV} must be an integer slice count, "
                    f"got {env!r}"
                ) from None
    if num_slices is None:
        indices = [
            getattr(d, "slice_index", None)
            for d in jax.devices()[:num_devices]
        ]
        distinct = {i for i in indices if i is not None}
        if len(distinct) <= 1 or None in indices:
            return None
        num_slices = len(distinct)
    if num_slices < 1:
        raise ValueError(f"comm slices must be >= 1, got {num_slices}")
    if num_devices % num_slices:
        raise ValueError(
            f"{num_devices} devices do not divide into {num_slices} "
            f"equal slices — pick a slice count dividing the data-axis "
            "width"
        )
    return CommTopology(
        num_slices=num_slices, slice_size=num_devices // num_slices
    )


def arrange_devices(devices, topology: CommTopology):
    """Order ``devices`` for ``topology``'s interleaved mesh convention.

    Devices with a real ``slice_index`` are grouped by slice and dealt
    round-robin so mesh position ``d`` lands on slice ``d % S`` (see
    CommTopology).  Devices without slice info (the virtual CPU mesh)
    keep their order — positions EMULATE slices there, which is the
    point of the override."""
    indices = [getattr(d, "slice_index", None) for d in devices]
    distinct = sorted({i for i in indices if i is not None})
    if len(distinct) != topology.num_slices:
        return list(devices)
    by_slice = {s: [] for s in distinct}
    for d, i in zip(devices, indices):
        by_slice[i].append(d)
    if any(
        len(members) != topology.slice_size for members in by_slice.values()
    ):
        raise ValueError(
            f"device slices are unequal "
            f"({[len(v) for v in by_slice.values()]} members) — "
            f"cannot arrange a {topology.num_slices}x"
            f"{topology.slice_size} topology"
        )
    out = []
    for r in range(topology.slice_size):
        for s in distinct:
            out.append(by_slice[s][r])
    return out


def make_mesh(
    num_devices: int | None = None,
    topology: CommTopology | None = None,
) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` global devices.

    With ``topology``: devices are ordered for the hierarchical
    collectives' interleaved slice convention (``arrange_devices``)."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    if topology is not None:
        if topology.num_devices != len(devices):
            raise ValueError(
                f"topology is {topology.num_slices}x{topology.slice_size} "
                f"= {topology.num_devices} devices, mesh has {len(devices)}"
            )
        devices = arrange_devices(devices, topology)
    return Mesh(np.asarray(devices), axis_names=(DATA_AXIS,))


def make_mesh_2d(num_data: int, num_space: int) -> Mesh:
    """2-D (data, space) mesh: batch over ``data``, image H over ``space``.

    Lay the space axis minor so each image's H shards sit on
    ICI-adjacent chips — the halo exchanges GSPMD inserts for spatially
    partitioned convs are neighbor traffic, exactly like ring attention's
    boundary passes.
    """
    devices = jax.devices()
    n = num_data * num_space
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(num_data, num_space)
    _assert_space_rows_single_process(grid)
    return Mesh(grid, axis_names=(DATA_AXIS, SPACE_AXIS))


def _assert_space_rows_single_process(grid) -> None:
    """Each space row (one image's H shards) must live on ONE process.

    Per-process batch assembly hands each process its own full-H images
    (``make_array_from_process_local_data``), so a space row straddling
    hosts would silently stitch H-slices of DIFFERENT hosts' images into
    one "global" image.  Guarded here — not only in the train.py CLI — so
    library callers fail the same way (ADVICE r3).  The check is on the
    actual device placement (not a per-host-count divisibility proxy), so
    valid sub-meshes — e.g. a space axis entirely on host 0's devices in
    a multi-host world — are not spuriously refused.
    """
    for row in grid:
        owners = {d.process_index for d in row}
        if len(owners) > 1:
            raise ValueError(
                f"space axis row {[str(d) for d in row]} spans processes "
                f"{sorted(owners)} — the space axis cannot span hosts: "
                "each image's H shards must sit on one process's devices "
                "(pick num_space dividing the per-host device count, or "
                "reorder/restrict the device list)"
            )


def make_local_mesh() -> Mesh:
    """1-D data mesh over THIS PROCESS's devices only.

    For per-host work in a multi-host job — e.g. the sharded eval pass,
    where each host detects its own slice of the val set on its own chips
    and results merge via a host-level all-gather (evaluate/detect.py) —
    compiled as an ordinary single-process program, no cross-host
    collectives.
    """
    return Mesh(np.asarray(jax.local_devices()), axis_names=(DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over the data axis."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def spatial_batch_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Per-key batch shardings for the 2-D (data, space) mesh: images shard
    batch over ``data`` AND image-H over ``space``; the per-image gt
    tensors shard over ``data`` only (replicated across the space axis)."""
    img = NamedSharding(mesh, PartitionSpec(DATA_AXIS, SPACE_AXIS))
    gt = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return {"images": img, "gt_boxes": gt, "gt_labels": gt, "gt_mask": gt}


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (params, optimizer state, scalars)."""
    return NamedSharding(mesh, PartitionSpec())
