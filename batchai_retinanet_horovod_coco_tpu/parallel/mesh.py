"""Device mesh construction and canonical shardings.

The flagship parallelism strategy is pure data-parallel over a 1-D ``data``
axis (SURVEY.md §2.4: DP is the only strategy the reference uses; TP/PP/SP
are deliberately not built for RetinaNet-R50, which fits per chip).  The mesh
abstraction still goes through ``jax.sharding.Mesh`` so that wider meshes
(e.g. a future ``spatial`` axis for XLA SPMD partitioning of very large
images) slot in without touching call sites.

Multi-host: ``jax.devices()`` returns the GLOBAL device list after
``jax.distributed.initialize`` (launch/pod.py), so the same mesh code serves
1 chip, one host with 8 chips, and a v5e-256 pod slice unchanged.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
# Second mesh axis for spatial (image-H) partitioning — the training-side
# sequence/context-parallel analogue (train.step.make_train_step_spatial,
# evaluate.detect.make_detect_fn_spatial).
SPACE_AXIS = "space"


def make_mesh(num_devices: int | None = None) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` global devices."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), axis_names=(DATA_AXIS,))


def make_mesh_2d(num_data: int, num_space: int) -> Mesh:
    """2-D (data, space) mesh: batch over ``data``, image H over ``space``.

    Lay the space axis minor so each image's H shards sit on
    ICI-adjacent chips — the halo exchanges GSPMD inserts for spatially
    partitioned convs are neighbor traffic, exactly like ring attention's
    boundary passes.
    """
    devices = jax.devices()
    n = num_data * num_space
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(num_data, num_space)
    _assert_space_rows_single_process(grid)
    return Mesh(grid, axis_names=(DATA_AXIS, SPACE_AXIS))


def _assert_space_rows_single_process(grid) -> None:
    """Each space row (one image's H shards) must live on ONE process.

    Per-process batch assembly hands each process its own full-H images
    (``make_array_from_process_local_data``), so a space row straddling
    hosts would silently stitch H-slices of DIFFERENT hosts' images into
    one "global" image.  Guarded here — not only in the train.py CLI — so
    library callers fail the same way (ADVICE r3).  The check is on the
    actual device placement (not a per-host-count divisibility proxy), so
    valid sub-meshes — e.g. a space axis entirely on host 0's devices in
    a multi-host world — are not spuriously refused.
    """
    for row in grid:
        owners = {d.process_index for d in row}
        if len(owners) > 1:
            raise ValueError(
                f"space axis row {[str(d) for d in row]} spans processes "
                f"{sorted(owners)} — the space axis cannot span hosts: "
                "each image's H shards must sit on one process's devices "
                "(pick num_space dividing the per-host device count, or "
                "reorder/restrict the device list)"
            )


def make_local_mesh() -> Mesh:
    """1-D data mesh over THIS PROCESS's devices only.

    For per-host work in a multi-host job — e.g. the sharded eval pass,
    where each host detects its own slice of the val set on its own chips
    and results merge via a host-level all-gather (evaluate/detect.py) —
    compiled as an ordinary single-process program, no cross-host
    collectives.
    """
    return Mesh(np.asarray(jax.local_devices()), axis_names=(DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over the data axis."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def spatial_batch_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Per-key batch shardings for the 2-D (data, space) mesh: images shard
    batch over ``data`` AND image-H over ``space``; the per-image gt
    tensors shard over ``data`` only (replicated across the space axis)."""
    img = NamedSharding(mesh, PartitionSpec(DATA_AXIS, SPACE_AXIS))
    gt = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    return {"images": img, "gt_boxes": gt, "gt_labels": gt, "gt_mask": gt}


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (params, optimizer state, scalars)."""
    return NamedSharding(mesh, PartitionSpec())
