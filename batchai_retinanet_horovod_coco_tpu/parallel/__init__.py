"""Distributed execution: device meshes, shardings, compiled collectives.

This package is the TPU-native replacement for the reference's ENTIRE
distributed runtime (SURVEY.md §2.3: Horovod python API H1, C++ core H2, NCCL
backend H3, MPI control plane H4):

- Horovod's background coordinator + tensor-fusion buffers have NO runtime
  equivalent here — gradient allreduce is ``jax.lax.pmean`` inside the
  jit-compiled step, which XLA fuses, schedules, and overlaps with backward
  compute at COMPILE TIME (the compile-time analogue of Horovod's fusion
  buffer, SURVEY.md H2);
- NCCL rings become ICI collectives emitted by XLA for the mesh's ``data``
  axis (DCN across pod slices);
- ``mpirun`` + MPI rank negotiation become ``jax.distributed.initialize``
  (see ``launch/pod.py``).
"""

from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
    DATA_AXIS,
    CommTopology,
    batch_sharding,
    derive_topology,
    make_mesh,
    replicated_sharding,
)
from batchai_retinanet_horovod_coco_tpu.parallel.zero import (
    clip_by_global_norm_sharded,
    init_sharded_opt_state,
    opt_state_partition_specs,
    sharded_update,
)

__all__ = [
    "DATA_AXIS",
    "CommTopology",
    "batch_sharding",
    "clip_by_global_norm_sharded",
    "derive_topology",
    "init_sharded_opt_state",
    "make_mesh",
    "opt_state_partition_specs",
    "replicated_sharding",
    "sharded_update",
]
