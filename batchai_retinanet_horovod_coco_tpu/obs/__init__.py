"""Runtime observability subsystem (ISSUE 3): trace spans, structured
events, stall watchdog.

Three coordinated pieces:

- ``obs.trace`` — low-overhead spans (ring-buffered per thread, Chrome
  ``trace_event`` JSON export, cross-process merge) and the subsystem's
  ONE clock (``monotonic_s``);
- ``obs.events`` — the structured JSONL sink (run headers, metrics,
  counters/gauges, device memory) that ``utils.metrics.MetricLogger``
  now shims over;
- ``obs.watchdog`` — the heartbeat registry every long-lived thread
  registers with, and the stall diagnoser that dumps the post-mortem
  before a timeout kills the run.

Two later additions ride on those three (ISSUE 9, imported lazily by
their consumers so the core trio stays import-light):

- ``obs.telemetry`` — the live metrics registry (counters/gauges/
  windowed histograms), Prometheus text exposition, watchdog-backed
  ``healthz``, and the drain-safe HTTP status server behind
  ``train.py --obs-port`` and the serve frontend's ``GET /metrics``;
- ``obs.slo`` — the declarative SLO monitor evaluating rules on that
  registry and emitting ``slo_violation`` events/trace instants.

``enable``/``finalize`` are the run-scoped bring-up/teardown the CLI
flags (``--obs-trace``/``--obs-dir``, utils/cli.py) call; everything in
between is always-on instrumentation that costs nothing while disabled.

Import order matters for jax-free processes (shm decode workers):
``trace`` and ``watchdog`` never import jax; ``events`` only touches it
lazily.  Keep it that way — a jax import in a decode worker violates
data/shm_pipeline.py's process contract.
"""

from __future__ import annotations

import os

from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.obs import watchdog
from batchai_retinanet_horovod_coco_tpu.obs import events

__all__ = [
    "trace", "watchdog", "events", "telemetry", "slo", "numerics",
    "enable", "finalize",
]


def __getattr__(name: str):
    # Lazy submodule access (``obs.telemetry`` / ``obs.slo`` /
    # ``obs.numerics``): keeps the package's import-time surface exactly
    # the PR-3 trio for jax-free worker processes that only need
    # trace/watchdog/events (numerics imports jax at module top).
    if name in ("telemetry", "slo", "numerics"):
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def enable(
    obs_dir: str,
    process_label: str = "main",
    stall_after: float = 120.0,
    sink=None,
    start_watchdog: bool = True,
) -> str:
    """Run-scoped bring-up: enable tracing into ``obs_dir`` (published to
    spawned children via the env contract) and start the stall watchdog
    (stack dumps land in ``obs_dir/watchdog_stacks.txt``)."""
    os.makedirs(obs_dir, exist_ok=True)
    trace.configure(obs_dir, process_label=process_label)
    if start_watchdog:
        watchdog.start(
            stall_after=stall_after,
            dump_path=os.path.join(obs_dir, "watchdog_stacks.txt"),
            sink=sink,
        )
    return obs_dir


def finalize() -> str | None:
    """Run-scoped teardown: export this process's trace, stop the
    watchdog, merge every per-process trace file (this process + any shm
    workers that exported on exit) into ``trace.json``.  Returns the
    merged path (None when tracing was never enabled)."""
    watchdog.stop()
    if not trace.enabled():
        return None
    trace.export()
    return trace.merge_traces()
