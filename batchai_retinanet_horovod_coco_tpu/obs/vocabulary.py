"""The event vocabulary: every structured-event / trace-instant /
telemetry-series NAME the tree emits, with its intended consumers.

The observability surface is stringly-typed — ``sink.event("repin", ...)``
on the producer side, ``ev["event"] == "repin"`` in a report section or
smoke check on the consumer side — and the PR 16 review round showed what
happens when the two drift (a metric said 2 repins, the parseable event
stream said 0).  This registry is the contract the static
``event-vocabulary`` rule (analysis/rules/event_vocabulary.py) enforces
tree-wide:

- every emit site's name literal must appear here (else
  *emitted-but-unregistered*);
- every entry must still be emitted somewhere (else *stale* or, worse,
  *consumed-but-never-emitted* when a declared consumer still reads it);
- every declared consumer path must be a real scanned file.

The rule parses this module STATICALLY (the dict below must stay a plain
literal — no comprehensions, no computed keys).  Entry shape:

``"name": {"kinds": (...), "consumers": (...)}``

- ``kinds`` — any of ``"event"`` (EventSink.event / emit_event JSONL),
  ``"instant"`` (trace.instant), ``"series"`` (telemetry counter/gauge/
  histogram constructors and trace.counter samples).
- ``consumers`` — repo-relative paths of the files that READ the name
  (report sections, SLO rules, bench checks, smoke drivers).  Empty means
  "emitted for ad-hoc analysis"; the rule only checks listed paths.

Runtime code may import :data:`VOCABULARY` (stdlib-only, jax-free) but
nothing requires it — the registry is primarily a static contract.
"""

from __future__ import annotations

#: name -> {"kinds": tuple[str, ...], "consumers": tuple[str, ...]}
VOCABULARY: dict[str, dict] = {
    "auto_resume": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
            "train.py",
        ),
    },
    "autoscale_decision": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
        ),
    },
    "autoscale_launch_failed": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "autoscaler_armed": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
        ),
    },
    "canary_promoted": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "canary_rollback": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
        ),
    },
    "canary_started": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "ckpt_saved": {
        "kinds": ("event",),
        "consumers": (
            "scripts/chaos.py",
        ),
    },
    "cost_analysis": {
        "kinds": ("instant",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "ef_reset": {
        "kinds": ("event",),
        "consumers": (
            "scripts/chaos.py",
        ),
    },
    "eval_consumer.qsize": {
        "kinds": ("series",),
        "consumers": (),
    },
    "fleet_breaker_close": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_breaker_half_open": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_breaker_open": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_redispatch": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_replica_died": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_replica_draining": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_replica_joined": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
        ),
    },
    "fleet_replica_removed": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_replica_respawned": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
            "scripts/fleet_obs_smoke.py",
        ),
    },
    "fleet_replica_spawned": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/chaos.py",
            "scripts/fleet_obs_smoke.py",
            "scripts/stream_smoke.py",
        ),
    },
    "fleet_request_latency_ms": {
        "kinds": ("series",),
        "consumers": (
            "scripts/chaos.py",
        ),
    },
    "fleet_respawn_failed": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "fleet_stream_reaped": {
        "kinds": ("event",),
        "consumers": (),
    },
    "numerics_trip": {
        "kinds": ("instant",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "scripts/numerics_smoke.py",
        ),
    },
    "perf_report_error": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "train.py",
        ),
    },
    "respawn_budget_exhausted": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "run_meta": {
        "kinds": ("instant",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "bench.py",
        ),
    },
    "serve.admission_qsize": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve.dispatch_qsize": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve.request_latency": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_batch_occupancy": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_free_slots": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_request_latency_ms": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "bench.py",
            "scripts/telemetry_smoke.py",
        ),
    },
    "serve_slot_wait_ms": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_stats": {
        "kinds": ("event",),
        "consumers": (),
    },
    "serve_stream_cache_hits_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_stream_cache_misses_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "serve_stream_frame_latency_ms": {
        "kinds": ("series",),
        "consumers": (),
    },
    "shm.inflight_batches": {
        "kinds": ("series",),
        "consumers": (),
    },
    "shm.out_qsize": {
        "kinds": ("series",),
        "consumers": (),
    },
    "slo_violation": {
        "kinds": ("event", "instant"),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
            "batchai_retinanet_horovod_coco_tpu/obs/slo.py",
            "scripts/fleet_obs_smoke.py",
            "scripts/numerics_smoke.py",
        ),
    },
    "stall": {
        "kinds": ("instant",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "stream_opened": {
        "kinds": ("instant",),
        "consumers": (),
    },
    "stream_repinned": {
        "kinds": ("event",),
        "consumers": (
            "scripts/stream_smoke.py",
        ),
    },
    "stream_session_reaped": {
        "kinds": ("instant",),
        "consumers": (),
    },
    "train_comm_compressed_bytes_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_comm_dcn_bytes_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_comm_ici_bytes_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_compiles_total": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_data_wait_fraction": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_data_wait_ms": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_ef_residual": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/slo.py",
        ),
    },
    "train_ef_residual_dcn": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_ef_saturation": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_grad_norm": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/slo.py",
        ),
    },
    "train_images_per_sec": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_last_compile_s": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_nonfinite_total": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/slo.py",
        ),
    },
    "train_replica_agreement": {
        "kinds": ("series",),
        "consumers": (),
    },
    "train_step": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
    "train_step_time_ms": {
        "kinds": ("series",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/slo.py",
        ),
    },
    "train_update_ratio": {
        "kinds": ("series",),
        "consumers": (),
    },
    "watchdog_stall": {
        "kinds": ("event",),
        "consumers": (
            "batchai_retinanet_horovod_coco_tpu/obs/analyze/report.py",
        ),
    },
}


def names() -> tuple[str, ...]:
    """Every registered name (sorted) — for runtime validation hooks."""
    return tuple(sorted(VOCABULARY))
