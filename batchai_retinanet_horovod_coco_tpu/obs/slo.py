"""SLO monitor: declarative rules over the telemetry registry (ISSUE 9).

The closing of the observability loop: the registry (obs/telemetry.py)
answers "how loaded is this process right now", this module answers "is
that within the budget we declared" — continuously, during the run, with
the verdict landing everywhere the post-hoc tooling already reads:

- one structured ``slo_violation`` event into the run's JSONL sink
  (metrics.jsonl, next to the metrics it indicts),
- one ``slo_violation`` trace instant (visible ON the Perfetto timeline
  at the moment of the breach, like the watchdog's stall markers),
- the ``violations`` section of PERF_REPORT.json (obs/analyze ranks a
  sustained violation ABOVE inferred bottlenecks, and ``tune
  --from-report`` consumes the mapped ops).

Rule shapes (all evaluated on ``Registry.snapshot()`` keys):

- **static ceiling/floor** — ``value OP threshold`` (p99 ceiling, stall
  count, data_wait fraction);
- **delta** — per-poll increase of a cumulative counter (shed RATE from
  ``serve_shed_total`` without a rate gauge);
- **regression vs a rolling window** — breach when the value exceeds
  ``factor ×`` the rolling median of its own recent healthy samples
  (step-time regression with no hand-picked absolute ceiling).

Anti-flap contract (pinned by tests/unit/test_telemetry.py): a rule
fires EXACTLY ONCE per sustained breach — the breach must hold for
``for_s`` before the event is emitted, the fired latch holds through the
rest of the breach, and only ``clear_s`` of continuous health re-arms
it.  ``check_once(now=...)`` is injectable so all of that is testable
without sleeping (the watchdog's pattern).

The monitor is read-only (it never sheds, kills, or throttles —
PARITY.md) and its poll thread is watchdog-registered: a wedged SLO
monitor is itself a diagnosed stall, not a silent gap in coverage.
"""

from __future__ import annotations

import dataclasses
import json
import re
import sys
import threading
from typing import Any, Callable

from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.telemetry import Registry
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One declarative objective over a snapshot metric.

    ``baseline_window > 0`` selects regression mode: the threshold is
    ``factor × median`` of the last ``baseline_window`` HEALTHY samples
    (breaching samples never poison their own baseline), armed only
    after ``min_baseline`` samples.  ``delta`` evaluates the per-poll
    increase instead of the value (cumulative counters → rates).
    """

    name: str
    metric: str  # a Registry.snapshot() key, e.g. "serve_request_latency_ms.p99"
    op: str = ">"  # breach when  value OP threshold  holds
    threshold: float | None = None
    for_s: float = 0.0  # breach must hold this long before firing
    clear_s: float = 10.0  # continuous health needed to re-arm
    delta: bool = False  # evaluate per-poll increase, not the value
    baseline_window: int = 0  # >0: regression vs rolling-median baseline
    factor: float = 1.5
    min_baseline: int = 5
    description: str = ""


_OPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


class _RuleState:
    __slots__ = (
        "breach_since", "healthy_since", "fired", "baseline", "last_raw",
        "last_value", "last_threshold",
    )

    def __init__(self):
        self.breach_since: float | None = None
        self.healthy_since: float | None = None
        self.fired = False
        self.baseline: list[float] = []
        self.last_raw: float | None = None  # previous cumulative (delta mode)
        self.last_value: float | None = None
        self.last_threshold: float | None = None


class SloMonitor:
    """Evaluate ``rules`` against ``registry.snapshot()`` on a poll loop.

    Violations are appended to ``self.violations`` (bounded), emitted to
    ``sink.event("slo_violation", ...)`` and ``trace.instant`` — plus one
    stderr line so an un-sinked run still shows the breach — and counted
    in the registry itself (``slo_violations_total{rule=...}``, scraped
    like everything else).
    """

    MAX_KEPT = 1000  # bounded memory over arbitrarily long runs

    def __init__(
        self,
        registry: Registry,
        rules: list[SloRule],
        sink: Any | None = None,
        poll_interval: float = 5.0,
        on_violation: Callable[[dict], None] | None = None,
    ):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names in {names}")
        self.registry = registry
        self.rules = list(rules)
        self.sink = sink
        self.poll_interval = poll_interval
        self.on_violation = on_violation
        self.violations: list[dict] = []
        self._fired_counts: dict[str, int] = {}
        self._states = {r.name: _RuleState() for r in self.rules}
        # Pull-based (a push counter would be gated on the global enable
        # bool, which a scrape-only serve monitor never sets).
        registry.register_collector(self._collect)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def _collect(self):
        for rule, n in sorted(self._fired_counts.items()):
            yield (
                "slo_violations_total", "counter",
                "slo_violation events fired, by rule", {"rule": rule},
                float(n),
            )

    # ---- evaluation ------------------------------------------------------

    def _evaluate(
        self, rule: SloRule, state: _RuleState, snap: dict[str, float]
    ) -> tuple[float | None, float | None, bool]:
        """(value, threshold, breached) for one rule on one snapshot.
        value None = no data this poll (missing metric, or the first
        sample of a delta rule) — treated as healthy-but-unknown."""
        raw = snap.get(rule.metric)
        if raw is None:
            return None, None, False
        if rule.delta:
            prev, state.last_raw = state.last_raw, raw
            if prev is None:
                return None, None, False
            value = raw - prev
        else:
            value = raw
        if rule.baseline_window > 0:
            threshold = None
            if len(state.baseline) >= rule.min_baseline:
                threshold = rule.factor * _median(state.baseline)
        else:
            threshold = rule.threshold
        breached = threshold is not None and _OPS[rule.op](value, threshold)
        if rule.baseline_window > 0 and not breached:
            # Healthy samples only: a sustained regression must not drag
            # its own baseline up until the breach "heals" by definition.
            state.baseline.append(value)
            if len(state.baseline) > rule.baseline_window:
                del state.baseline[: -rule.baseline_window]
        return value, threshold, breached

    def check_once(self, now: float | None = None) -> list[dict]:
        """One poll: returns the violations that FIRED this poll (usually
        empty).  Injectable ``now`` makes the sustain/re-arm state machine
        testable without sleeping."""
        now = monotonic_s() if now is None else now
        snap = self.registry.snapshot()
        fired: list[dict] = []
        for rule in self.rules:
            state = self._states[rule.name]
            value, threshold, breached = self._evaluate(rule, state, snap)
            state.last_value, state.last_threshold = value, threshold
            if breached:
                state.healthy_since = None
                if state.breach_since is None:
                    state.breach_since = now
                if (
                    not state.fired
                    and now - state.breach_since >= rule.for_s
                ):
                    state.fired = True  # once per sustained breach
                    fired.append(
                        {
                            "rule": rule.name,
                            "metric": rule.metric,
                            "op": rule.op,
                            "value": round(float(value), 4),
                            "threshold": round(float(threshold), 4),
                            "sustained_s": round(now - state.breach_since, 3),
                            "description": rule.description,
                        }
                    )
            else:
                state.breach_since = None
                if state.fired:
                    if state.healthy_since is None:
                        state.healthy_since = now
                    if now - state.healthy_since >= rule.clear_s:
                        state.fired = False  # re-armed for the next breach
        for v in fired:
            self._emit(v)
        return fired

    def _emit(self, violation: dict) -> None:
        self.violations.append(violation)
        if len(self.violations) > self.MAX_KEPT:
            del self.violations[: -self.MAX_KEPT]
        self._fired_counts[violation["rule"]] = (
            self._fired_counts.get(violation["rule"], 0) + 1
        )
        # Timeline marker first (no-op while tracing is off), then the
        # JSONL record, then one unmissable stderr line — same layering
        # as the watchdog's stall dump.
        trace.instant(
            "slo_violation",
            rule=violation["rule"],
            metric=violation["metric"],
            value=violation["value"],
            threshold=violation["threshold"],
            sustained_s=violation["sustained_s"],
        )
        if self.sink is not None:
            try:
                self.sink.event("slo_violation", **violation)
            except Exception:
                pass  # a broken sink must not mask the stderr line
        print(
            json.dumps({"event": "slo_violation", **violation}),
            file=sys.stderr, flush=True,
        )
        if self.on_violation is not None:
            self.on_violation(violation)

    def status(self) -> dict:
        """Per-rule live state (the /statusz debugging view)."""
        out = {}
        for rule in self.rules:
            s = self._states[rule.name]
            out[rule.name] = {
                "metric": rule.metric,
                "value": s.last_value,
                "threshold": s.last_threshold,
                "breaching": s.breach_since is not None,
                "fired": s.fired,
            }
        return out

    # ---- poll thread -----------------------------------------------------

    def _run(self, hb: watchdog.Heartbeat) -> None:
        try:
            while not self._stop.wait(self.poll_interval):
                hb.beat()
                self.check_once()
        except BaseException as e:
            # The monitor must never die silently: a crashed poll thread
            # silently disarms every SLO for the rest of the run.
            print(
                json.dumps(
                    {"event": "slo_monitor_crashed", "error": repr(e)}
                ),
                file=sys.stderr, flush=True,
            )
            raise
        finally:
            hb.close()

    def start(self) -> "SloMonitor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        hb = watchdog.register("slo-monitor")
        self._thread = threading.Thread(
            target=self._run, args=(hb,), daemon=True, name="slo-monitor"
        )
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Signal the poll loop to exit WITHOUT joining — safe to call
        from the poll thread itself (e.g. an ``on_violation`` handler
        that terminally resolves the monitored condition, like the
        fleet canary gate's rollback).  ``stop()`` from another thread
        still performs the full join + final evaluation."""
        self._stop.set()

    def stop(self) -> None:
        started = self._thread is not None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if started:
            # One final evaluation at drain: a run shorter than one poll
            # interval (offline serve mode, smoke configs) must still get
            # its rules evaluated at least once — an end-of-run breach is
            # a breach, not a race against the poll clock.
            self.check_once()


def _median(values: list[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


# ---------------------------------------------------------------------------
# Built-in rules + the CLI grammar
# ---------------------------------------------------------------------------


def stall_rule(for_s: float = 0.0) -> SloRule:
    """Fires when the watchdog reports any non-idle component past its
    stall budget (the registry's ``watchdog_stalled`` gauge)."""
    return SloRule(
        name="watchdog-stall",
        metric="watchdog_stalled",
        op=">",
        threshold=0.0,
        for_s=for_s,
        description="a watchdog component is past its stall budget",
    )


def p99_ceiling(
    ceiling_ms: float,
    metric: str = "serve_request_latency_ms.p99",
    for_s: float = 10.0,
) -> SloRule:
    return SloRule(
        name="p99-ceiling",
        metric=metric,
        op=">",
        threshold=ceiling_ms,
        for_s=for_s,
        description=f"windowed p99 above the {ceiling_ms} ms ceiling",
    )


def shed_rate(
    max_per_poll: float,
    metric: str = "serve_shed_total",
    for_s: float = 0.0,
) -> SloRule:
    return SloRule(
        name="shed-rate",
        metric=metric,
        delta=True,
        op=">",
        threshold=max_per_poll,
        for_s=for_s,
        description=f"more than {max_per_poll} requests shed per poll",
    )


def step_time_regression(
    factor: float = 1.5,
    window: int = 32,
    metric: str = "train_step_time_ms",
    for_s: float = 30.0,
) -> SloRule:
    return SloRule(
        name="step-time-regression",
        metric=metric,
        op=">",
        baseline_window=window,
        factor=factor,
        for_s=for_s,
        description=(
            f"step time above {factor}x its rolling-median baseline"
        ),
    )


def nonfinite_rule(metric: str = "train_nonfinite_total") -> SloRule:
    """Fires IMMEDIATELY (no sustain, no baseline) on any non-finite
    gradient element or tripped finite-check (ISSUE 10: the loop's
    abort path and the in-step summary both feed the counter).  A NaN is
    never a transient — ``for_s=0`` and the fired latch never re-arms in
    practice because the counter is monotonic within a run."""
    return SloRule(
        name="train-nonfinite",
        metric=metric,
        op=">",
        threshold=0.0,
        for_s=0.0,
        description=(
            "non-finite values in the gradient/update stream "
            "(NUMERICS_DUMP.json has the provenance)"
        ),
    )


def ckpt_staleness_rule(
    factor: float = 2.0,
    metric: str = "ckpt_staleness",
    for_s: float = 0.0,
) -> SloRule:
    """Fires when training has ADVANCED ``factor ×`` the measured
    steps-between-saves past the last successful checkpoint (ISSUE 11):
    a silently wedged or crash-looping saver is otherwise invisible
    until the run dies and resume discovers hours of lost work.  The
    metric is the telemetry plane's STEP-based ``ckpt_staleness`` pull
    gauge (obs/telemetry.py, present once two saves have landed) — not
    the wall-clock age, which a legitimate multi-minute sync eval or
    cold compile inflates while no step runs; steps only advance when
    the loop is actually training past its save cadence."""
    return SloRule(
        name="ckpt-staleness",
        metric=metric,
        op=">",
        threshold=factor,
        for_s=for_s,
        description=(
            f"training advanced {factor}x the save cadence with no "
            "checkpoint landing (saver wedged/dying; see "
            "ckpt_write_error on stderr and the ckpt-writer watchdog "
            "component)"
        ),
    )


def grad_norm_spike(
    factor: float = 10.0,
    window: int = 32,
    metric: str = "train_grad_norm",
    for_s: float = 0.0,
) -> SloRule:
    """Pre-divergence tripwire: the pre-clip global gradient norm vs
    ``factor ×`` the rolling median of its own HEALTHY history (the SLO
    regression mode — no hand-picked absolute ceiling, and breaching
    samples never poison the baseline).  Loose factor by default: the
    clip chain absorbs ordinary spikes; a 10x sustained departure is the
    loss-about-to-diverge signature worth a page."""
    return SloRule(
        name="grad-norm-spike",
        metric=metric,
        op=">",
        baseline_window=window,
        factor=factor,
        for_s=for_s,
        description=(
            f"pre-clip grad norm above {factor}x its rolling-median "
            "baseline"
        ),
    )


def fleet_availability_rule(
    floor: float = 0.999,
    metric: str = "fleet_availability",
    for_s: float = 0.0,
) -> SloRule:
    """Fleet-level availability floor (ISSUE 15): fires when the
    fraction of ROUTABLE replicas (breaker CLOSED) over non-drained
    replicas drops below ``floor`` — i.e. when ANY replica is lost, at
    the default.  The metric is the fleet router's ``fleet_availability``
    gauge on its federated registry; the anti-flap machinery makes a
    replica death page exactly once per sustained loss (the breaker
    readmitting the respawned replica heals the breach and, after
    ``clear_s``, re-arms the rule).  Silent on registries without the
    gauge, so it is safe to arm everywhere the fleet monitor runs."""
    return SloRule(
        name="fleet-availability",
        metric=metric,
        op="<",
        threshold=floor,
        for_s=for_s,
        description=(
            f"routable-replica fraction below {floor} (a replica's "
            "breaker is open or the replica is gone; see the "
            "fleet_breaker_open events on the timeline)"
        ),
    )


def fleet_occupancy_rule(
    ceiling: float = 0.97,
    metric: str = "fleet_occupancy",
    for_s: float = 30.0,
) -> SloRule:
    """Fleet saturation floor-to-ceiling tripwire (ISSUE 19): fires
    when mean live slot occupancy across ROUTABLE replicas (the fleet
    router's ``fleet_occupancy`` gauge — draining replicas excluded)
    stays pinned at ``ceiling`` for ``for_s``.  With the autoscaler
    armed this can only sustain when scale-ups are capped at
    ``max_replicas`` — i.e. the fleet is underprovisioned BY POLICY,
    which is a page, not a scale decision; without the autoscaler it is
    the "arm --autoscale or add replicas" signal.  Silent on registries
    without the gauge (single-replica serve, idle fleets), so it is
    safe to arm wherever the fleet monitor runs."""
    return SloRule(
        name="fleet-occupancy-saturated",
        metric=metric,
        op=">=",
        threshold=ceiling,
        for_s=for_s,
        description=(
            f"fleet slot occupancy pinned at >= {ceiling} for {for_s:g}s "
            "(capacity saturated; autoscale capped or not armed — see "
            "the autoscale_decision events and fleet_scale_capped_total)"
        ),
    )


def ef_residual_spike(
    factor: float = 10.0,
    window: int = 32,
    metric: str = "train_ef_residual",
    for_s: float = 0.0,
    hop: str | None = None,
) -> SloRule:
    """Gradient-compression health tripwire (ISSUE 13): the error-
    feedback residual norm vs ``factor ×`` its own rolling-median
    HEALTHY baseline.  A compressed gradient degrading training shows
    up here first — a residual spike means the per-block int8 scales
    stopped fitting the gradient distribution (saturation), i.e. the
    quantizer is now dropping signal the optimizer needed.  Regression
    mode, like ``grad_norm_spike``: no absolute ceiling to hand-pick,
    and the rule stays silent on runs without compression (the
    ``train_ef_residual`` gauge never exists), so it is ALWAYS armed in
    train.py's built-in rule set.

    ``hop`` labels the rule per fabric hop of the hierarchical tree
    (ISSUE 16): ``hop="dcn"`` watches the ``train_ef_residual_dcn``
    gauge — the cross-slice hop, the only one that quantizes — under
    the name ``ef_residual_spike_dcn``.  Same silent-without-the-gauge
    contract, so the hop variant is armed unconditionally too."""
    if hop is not None:
        metric = f"train_ef_residual_{hop}"
    return SloRule(
        name="ef_residual_spike" if hop is None else f"ef_residual_spike_{hop}",
        metric=metric,
        op=">",
        baseline_window=window,
        factor=factor,
        for_s=for_s,
        description=(
            f"gradient-compression EF residual above {factor}x its "
            "rolling-median baseline (per-block scales saturating; "
            "compressed gradients dropping signal)"
            + (f" [{hop} hop of the hierarchical tree]" if hop else "")
        ),
    )


#: ``--slo-rule`` grammar:  METRIC OP THRESHOLD [@FOR_S]
#: where OP ∈ {>, >=, <, <=} and THRESHOLD is either a number (static
#: ceiling/floor) or ``xFACTOR`` (regression vs the rolling-median
#: baseline), e.g. ``serve_request_latency_ms.p99>250@30`` or
#: ``train_step_time_ms>x1.5@60``.
_RULE_RE = re.compile(
    r"^(?P<metric>[^<>=@\s]+)\s*(?P<op>>=|<=|>|<)\s*"
    r"(?P<thr>x?[-+0-9.eE]+)\s*(?:@\s*(?P<for>[0-9.]+))?$"
)


def parse_rule(spec: str) -> SloRule:
    """One ``--slo-rule`` spec → an ``SloRule`` (see ``_RULE_RE``)."""
    m = _RULE_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad SLO rule {spec!r}: expected METRIC{{>,>=,<,<=}}THRESHOLD"
            "[@FOR_S], e.g. 'serve_request_latency_ms.p99>250@30' or "
            "'train_step_time_ms>x1.5@60' (x = regression factor vs a "
            "rolling-median baseline)"
        )
    metric, op, thr = m.group("metric"), m.group("op"), m.group("thr")
    for_s = float(m.group("for") or 0.0)
    # The op spelled out in the generated name: sanitizing '>' and '<'
    # both to '_' would collide a floor and a ceiling on one metric into
    # "duplicate SLO rule names" at startup.
    op_name = {">": "gt", ">=": "ge", "<": "lt", "<=": "le"}[op]
    name = re.sub(
        r"[^A-Za-z0-9_.-]", "_", f"{metric}_{op_name}_{thr}@{for_s:g}"
    )
    if thr.startswith("x"):
        return SloRule(
            name=name, metric=metric, op=op, for_s=for_s,
            baseline_window=32, factor=float(thr[1:]),
            description=f"declared via --slo-rule {spec!r}",
        )
    return SloRule(
        name=name, metric=metric, op=op, threshold=float(thr), for_s=for_s,
        description=f"declared via --slo-rule {spec!r}",
    )
