"""Low-overhead structured trace spans → Chrome ``trace_event`` JSON.

The attribution half of the observability subsystem (ISSUE 3): PRs 1–2 grew
four concurrent machines (shm decode workers, the device-prefetch thread,
the eval consumer, async mid-training eval) whose interleaving decides
whether the chips are fed — and ``bench.py`` can only measure end-to-end.
This module records *where the time went*: named spans, ring-buffered per
thread, exported as Chrome ``trace_event`` JSON that Perfetto/``chrome://
tracing`` renders as one aligned timeline with a track per thread and a
process group per OS process (shm workers included).

Design constraints, in priority order:

1. **Nil disabled-path overhead.**  ``span()`` checks one module-level bool
   and returns a shared no-op context manager; no allocation, no clock
   read, no lock.  The hot step loop keeps its spans unconditionally.
2. **No jax import.**  The shm decode workers trace their decodes and must
   never pull jax into a data-layer process (data/shm_pipeline.py's
   contract).  Anything needing jax (device metadata) lives in
   ``obs.events`` behind lazy imports.
3. **Lock-free recording.**  Each thread appends to its own bounded
   ``deque`` (the ring); the global registry lock is taken only at ring
   creation and export.  A full ring drops the OLDEST events (the tail of
   a run is what a stall post-mortem needs).

Clock contract (the ONE clock, ISSUE 3 satellite): ``monotonic_s()`` is the
timestamp source for spans AND for the JSONL event sink (obs/events.py), so
trace and metrics timestamps align exactly.  For cross-process alignment the
exporter maps monotonic times onto the wall clock via a (wall, perf) anchor
pair captured at import — processes on one host share ``time.time()``, so
worker tracks line up with the main loop's without a handshake.

Cross-thread/cross-process spans: ``begin()`` returns a handle that any
thread may ``end()`` (the span lands on the *beginning* thread's track —
e.g. a batch's life from submit to assembly).  Cross-process spans are just
each process recording its own complete spans; ``merge_traces`` stitches
the per-process JSON files (each worker exports its own on clean exit) into
one ``trace.json``.

Child-process propagation: ``configure()`` exports ``RETINANET_OBS_DIR`` so
``spawn``-ed children (the shm workers) can self-enable via
``maybe_configure_from_env()`` without widening any pickled config surface.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Any, Iterator

# Env var contract shared with spawned children (data/shm_pipeline.py
# workers): presence = tracing on, value = the trace/artifact directory.
OBS_DIR_ENV = "RETINANET_OBS_DIR"
# Best-effort process index for multi-host merges (main process resolves
# it from jax lazily; children inherit whatever the parent had resolved).
OBS_PINDEX_ENV = "RETINANET_OBS_PINDEX"
# The run id scoping this run's per-process trace files: pids are never
# reused within a run but ARE across runs, so without a run token a
# reused --obs-dir would merge stale partials from previous runs into
# trace.json.  Children inherit the parent's id via this env var.
OBS_RUN_ENV = "RETINANET_OBS_RUN"

# Cross-process request tracing (ISSUE 15): the fleet frontend mints one
# fleet-wide trace id per request and carries it to replicas in this HTTP
# header; replica frontends tag their ``serve_request`` span (and its flow
# marker) with it and echo it back on the response, so one slow request is
# followable edge → router → replica → response across the merged trace's
# process tracks.
TRACE_HEADER = "X-Retinanet-Trace"

DEFAULT_CAPACITY = 65536

# (wall, perf) anchor pair: monotonic_s() times map onto the shared wall
# clock as  wall = _WALL_ANCHOR + (t - _PERF_ANCHOR).  Captured once at
# import so every ring in this process shares one mapping.
_WALL_ANCHOR = time.time()  # lint: monotonic-clock: the wall half of the anchor — wall time IS the point here
_PERF_ANCHOR = time.perf_counter()  # lint: monotonic-clock: the perf half of the anchor monotonic_s() maps through

_enabled = False
_trace_dir: str | None = None
_capacity = DEFAULT_CAPACITY
_process_label = "main"
_run_id: str | None = None
_config_pid: int | None = None  # which process this config belongs to

_registry_lock = threading.Lock()
_rings: list["_Ring"] = []
_tls = threading.local()


def monotonic_s() -> float:
    """THE timestamp source for the whole obs subsystem (spans, JSONL
    events, watchdog heartbeats): monotonic, sub-µs resolution, immune to
    wall-clock steps.  Use this instead of ``time.time()`` /
    ``time.perf_counter()`` in instrumented code so every timestamp in a
    run is mutually comparable."""
    # lint: monotonic-clock: this IS the one clock's implementation
    return time.perf_counter()


def to_wall(t: float) -> float:
    """Map a ``monotonic_s()`` timestamp onto the wall clock (seconds since
    epoch) — the exporter's cross-process alignment."""
    return _WALL_ANCHOR + (t - _PERF_ANCHOR)


def enabled() -> bool:
    return _enabled


# Synthetic per-ring track ids: OS thread idents RECYCLE (a dead eval
# pipeline's coordinator and a later prefetch thread can share an ident),
# which would interleave two different threads' spans on one Perfetto
# track.  A ring is per thread LIFETIME (thread-local), so a fresh id per
# ring keeps every thread's spans on its own track.
_next_tid = 1
# Bumped by reset(): a thread whose thread-local ring predates the last
# reset would otherwise keep appending to a ring no longer in the
# registry — every event silently lost.  _ring() re-registers instead.
_generation = 0


class _Ring:
    """One thread's bounded event buffer.  Events are tuples
    ``(ph, name, t_s, dur_s_or_value, args_or_None)`` with ``ph`` the
    Chrome phase ("X" complete, "i" instant, "C" counter, "s"/"t"/"f"
    flow start/step/end)."""

    __slots__ = ("events", "tid", "thread_name", "appended", "gen")

    def __init__(self, capacity: int):
        global _next_tid
        self.events: collections.deque = collections.deque(maxlen=capacity)
        with _registry_lock:
            self.tid = _next_tid
            _next_tid += 1
        t = threading.current_thread()
        self.thread_name = t.name
        self.appended = 0
        self.gen = _generation

    def add(self, ev: tuple) -> None:
        self.appended += 1
        self.events.append(ev)

    @property
    def dropped(self) -> int:
        return self.appended - len(self.events)


# Bound on distinct per-thread rings (= Perfetto tracks): request-scoped
# spans on thread-per-request HTTP handler threads (the serve/fleet
# frontends) would otherwise register one permanent ring per REQUEST for
# the life of the process.  Threads beyond the cap share one overflow
# ring — deque.append is GIL-atomic, so the only degradation is that
# their spans merge onto a single labeled track instead of growing
# memory without bound.
MAX_RINGS = 4096
_overflow_ring: "_Ring | None" = None


def _ring() -> _Ring:
    r = getattr(_tls, "ring", None)
    if r is None or r.gen != _generation:  # stale after a reset()
        global _overflow_ring
        with _registry_lock:
            at_cap = len(_rings) >= MAX_RINGS
        if at_cap:
            r = _overflow_ring
            if r is None or r.gen != _generation:
                r = _Ring(_capacity)
                r.thread_name = "overflow (ring cap)"
                with _registry_lock:
                    _rings.append(r)
                _overflow_ring = r
            _tls.ring = r
        else:
            r = _tls.ring = _Ring(_capacity)
            with _registry_lock:
                _rings.append(r)
    return r


class _NullSpan:
    """The shared disabled-path span: no state, no clock, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "t0")

    def __init__(self, name: str, args: dict | None):
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = monotonic_s()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = monotonic_s()
        _ring().add(("X", self.name, self.t0, t1 - self.t0, self.args))
        return False


def span(name: str, **args: Any):
    """Context manager timing a named region on the current thread's track.

    Disabled: returns the shared no-op singleton (one bool check).  Keyword
    args become the Chrome event's ``args`` payload — avoid them on
    per-step hot paths (the dict is built before the enabled check)."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def begin(name: str, **args: Any):
    """Explicit begin half of a cross-thread span: the returned handle may
    be ``end()``-ed by ANY thread; the span lands on the beginning thread's
    track.  Returns None when disabled (``end(None)`` is a no-op)."""
    if not _enabled:
        return None
    return (name, monotonic_s(), _ring(), args or None)


def end(handle) -> None:
    """Complete a ``begin()`` handle (any thread)."""
    if handle is None or not _enabled:
        return
    name, t0, ring, args = handle
    ring.add(("X", name, t0, monotonic_s() - t0, args))


def instant(name: str, **args: Any) -> None:
    """A zero-duration marker event on the current thread's track."""
    if not _enabled:
        return
    _ring().add(("i", name, monotonic_s(), 0.0, args or None))


def counter(name: str, value: float) -> None:
    """A Chrome counter sample (queue depth, occupancy, bytes-in-use):
    renders as a stacked-area track in Perfetto."""
    if not _enabled:
        return
    _ring().add(("C", name, monotonic_s(), float(value), None))


def new_trace_id() -> str:
    """Mint one fleet-wide request trace id (the value carried in
    ``TRACE_HEADER`` and tagged onto every span the request touches)."""
    return uuid.uuid4().hex[:16]


def _flow(ph: str, name: str, flow_id) -> None:
    if not _enabled:
        return
    _ring().add((ph, name, monotonic_s(), 0.0, {"id": str(flow_id)}))


def flow_start(name: str, flow_id) -> None:
    """Begin a Chrome flow (the arrow Perfetto draws between slices on
    different tracks).  Emit INSIDE the slice the arrow should leave from
    (binding is by enclosing slice); ``flow_step``/``flow_end`` with the
    same (name, id) continue it on other threads/processes — the visual
    follow-the-request mechanism for fleet traces."""
    _flow("s", name, flow_id)


def flow_step(name: str, flow_id) -> None:
    _flow("t", name, flow_id)


def flow_end(name: str, flow_id) -> None:
    _flow("f", name, flow_id)


def configure(
    trace_dir: str,
    capacity: int = DEFAULT_CAPACITY,
    process_label: str = "main",
    export_env: bool = True,
) -> None:
    """Enable tracing process-wide.  ``export_env`` (default) publishes
    ``RETINANET_OBS_DIR`` + a fresh run id so spawned children (shm
    workers) self-enable — and export under the SAME run id — via
    ``maybe_configure_from_env``.  ``export_env=False`` (children) adopts
    the inherited run id instead of minting one."""
    global _enabled, _trace_dir, _capacity, _process_label, _run_id
    global _config_pid
    os.makedirs(trace_dir, exist_ok=True)
    _trace_dir = trace_dir
    _capacity = capacity
    _process_label = process_label
    _config_pid = os.getpid()
    if export_env:
        _run_id = uuid.uuid4().hex[:8]
        os.environ[OBS_DIR_ENV] = trace_dir
        os.environ[OBS_RUN_ENV] = _run_id
    else:
        _run_id = os.environ.get(OBS_RUN_ENV) or uuid.uuid4().hex[:8]
    _enabled = True


def run_id() -> str | None:
    """This run's trace-file scoping token (None until configured)."""
    return _run_id


def trace_dir() -> str | None:
    """The configured obs artifact directory (None while disabled) — the
    default landing spot for failure-path artifacts that belong next to
    the trace (the numerics NUMERICS_DUMP.json, train/loop.py)."""
    return _trace_dir if _enabled else None


def maybe_configure_from_env(process_label: str) -> bool:
    """Child-process bring-up: enable tracing iff the parent exported
    ``RETINANET_OBS_DIR`` before the spawn.  Never re-exports the env (the
    child inherited it already).

    FORK-started children inherit ``_enabled`` along with the parent's
    recorded rings; treating that as "already configured" would re-export
    every pre-fork parent span under the child's pid (duplicated on the
    merged timeline) with the parent's label.  The recorded config pid
    tells the cases apart: same pid = genuinely configured, different
    pid = inherited — drop the inherited rings and re-label."""
    if _enabled:
        if _config_pid == os.getpid():
            return True
        global _generation
        with _registry_lock:
            _rings.clear()  # the parent owns those events, not this child
            _generation += 1
    trace_dir = os.environ.get(OBS_DIR_ENV)
    if not trace_dir:
        return False
    configure(trace_dir, process_label=process_label, export_env=False)
    return True


def _process_index() -> int | None:
    """Best-effort multi-host process index, with NO side effects: jax is
    consulted only when it is already imported AND its backend is already
    initialized.  Calling ``jax.process_index()`` any earlier would
    initialize the backend itself — before train.py applies
    ``--platform``/``XLA_FLAGS``/``jax.distributed.initialize`` — and
    freeze the wrong platform for the whole process (observed: the
    8-device virtual CPU mesh collapsing to 1 device when configure ran
    first).  Workers read the env value the parent publishes once its
    backend is up (obs/events.py run header).  None = unknown."""
    v = os.environ.get(OBS_PINDEX_ENV)
    if v is not None:
        try:
            return int(v)
        except ValueError:
            pass
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return None  # backend not up yet; resolving would init it
        return int(jax.process_index())
    except Exception:
        return None


def _chrome_events() -> Iterator[dict]:
    """This process's rings → Chrome trace_event dicts (ts/dur in µs on
    the shared wall timeline)."""
    pid = os.getpid()
    with _registry_lock:
        rings = list(_rings)
    pindex = _process_index()
    pname = f"p{pindex if pindex is not None else '?'}:{_process_label}"
    yield {
        "ph": "M", "name": "process_name", "pid": pid,
        "args": {"name": f"{pname} (pid {pid})"},
    }
    if pindex is not None:
        yield {
            "ph": "M", "name": "process_labels", "pid": pid,
            "args": {"labels": f"process_index={pindex}"},
        }
    for ring in rings:
        yield {
            "ph": "M", "name": "thread_name", "pid": pid, "tid": ring.tid,
            "args": {"name": ring.thread_name},
        }
        for ph, name, t, dur, args in list(ring.events):
            ts = int(to_wall(t) * 1e6)
            if ph == "X":
                ev = {
                    "ph": "X", "cat": "obs", "name": name, "ts": ts,
                    "dur": max(0, int(dur * 1e6)), "pid": pid,
                    "tid": ring.tid,
                }
                if args:
                    ev["args"] = args
            elif ph == "C":
                ev = {
                    "ph": "C", "cat": "obs", "name": name, "ts": ts,
                    "pid": pid, "tid": ring.tid, "args": {"value": dur},
                }
            elif ph in ("s", "t", "f"):
                # Flow events: same (cat, name, id) across processes link
                # into one Perfetto arrow chain; "bp": "e" binds each to
                # its enclosing slice on this track.
                ev = {
                    "ph": ph, "cat": "obs.flow", "name": name, "ts": ts,
                    "pid": pid, "tid": ring.tid,
                    "id": (args or {}).get("id"), "bp": "e",
                }
            else:
                ev = {
                    "ph": "i", "cat": "obs", "name": name, "ts": ts,
                    "s": "t", "pid": pid, "tid": ring.tid,
                }
                if args:
                    ev["args"] = args
            yield ev


def snapshot_events() -> list[dict]:
    """This process's recorded events as Chrome ``trace_event`` dicts —
    the export payload without the file.  The inline analysis hooks
    (``obs.analyze.span_attribution`` in ``bench.py --trace``) read the
    live rings through this; empty while tracing is disabled."""
    if not _enabled:
        return []
    return list(_chrome_events())


def export(path: str | None = None) -> str | None:
    """Write this process's events as one Chrome-trace JSON file.

    Default path: ``<trace_dir>/trace-<label>-<pid>.json`` — per-process
    names so concurrent exporters (shm workers) never clobber.  Returns the
    path written, or None when tracing is disabled."""
    if not _enabled:
        return None
    if path is None:
        assert _trace_dir is not None
        path = os.path.join(
            _trace_dir,
            f"trace-{_run_id}-{_process_label}-{os.getpid()}.json",
        )
    dropped = sum(r.dropped for r in _rings)
    doc = {
        "traceEvents": snapshot_events(),
        "displayTimeUnit": "ms",
        "otherData": {
            "process_label": _process_label,
            "pid": os.getpid(),
            "events_dropped_by_ring": dropped,
            "wall_anchor_s": _WALL_ANCHOR,
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)  # merge never reads a half-written file
    return path


def merge_traces(
    trace_dir: str | None = None, out_name: str = "trace.json"
) -> str | None:
    """Stitch THIS RUN's per-process ``trace-<run_id>-*.json`` files in
    ``trace_dir`` into one Perfetto-loadable file.  Scoped by run id: a
    reused obs dir keeps previous runs' partials on disk, and merging
    them would put hours-old spans on the wall-aligned timeline.  Call
    AFTER the pipelines closed (workers export on clean exit, and close()
    joins them first).  Unreadable partials are skipped with a note in
    ``otherData`` rather than failing the merge."""
    trace_dir = trace_dir or _trace_dir
    if trace_dir is None:
        return None
    prefix = f"trace-{_run_id}-" if _run_id else "trace-"
    events: list[dict] = []
    merged_from: list[str] = []
    skipped: list[str] = []
    for name in sorted(os.listdir(trace_dir)):
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        p = os.path.join(trace_dir, name)
        try:
            with open(p) as f:
                events.extend(json.load(f)["traceEvents"])
            merged_from.append(name)
        except (OSError, ValueError, KeyError):
            skipped.append(name)
    out = os.path.join(trace_dir, out_name)
    # Atomic like the per-process exports above: the perf doctor and
    # Perfetto both scan for trace.json by name (utils.atomicio is
    # jax-free — this module's import contract holds).  STREAMED into
    # the tmp file: a long run's merged events are large, and a full
    # json.dumps string would double peak memory at finalize.
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_writer,
    )

    with atomic_writer(out) as f:
        json.dump(
            {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "merged_from": merged_from,
                    "skipped": skipped,
                },
            },
            f,
        )
    return out


def reset() -> None:
    """Test hook: disable and drop all recorded state (including the env
    contract, so a later test's spawned children don't self-enable)."""
    global _enabled, _trace_dir, _process_label, _capacity, _run_id
    _enabled = False
    _trace_dir = None
    _process_label = "main"
    _capacity = DEFAULT_CAPACITY
    _run_id = None
    os.environ.pop(OBS_DIR_ENV, None)
    os.environ.pop(OBS_PINDEX_ENV, None)
    os.environ.pop(OBS_RUN_ENV, None)
    global _generation, _overflow_ring
    _overflow_ring = None
    with _registry_lock:
        _rings.clear()
        # Invalidate EVERY thread's cached thread-local ring (not just the
        # caller's): a live thread's next event re-registers a fresh ring
        # instead of appending to an orphaned one.
        _generation += 1
