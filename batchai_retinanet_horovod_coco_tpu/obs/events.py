"""Unified structured-event sink: one JSONL stream for metrics AND events.

Subsumes the 89-line ``utils/metrics.MetricLogger`` (which survives as a
thin compat shim over this class) and fixes its two recorded holes
(ISSUE 3 satellites):

- ``_scalarize`` silently dropped non-castable metrics and let non-finite
  ones through indistinguishably.  A NaN loss is the single most
  important value a run ever logs — it is now announced LOUDLY on stdout
  on top of the record (JSONL keeps it as a bare ``NaN`` token, the
  Python ``json`` default, which ``split_runs`` reads back); non-castable
  values are counted and named in the record (``dropped_metrics``)
  instead of vanishing.
- ``metrics.jsonl`` was opened in append mode with no run delimiter, so a
  resumed/re-run directory concatenated runs indistinguishably.  Every
  sink now opens with a ``run_header`` record (run id, wall time, clock
  anchor, device kind, process count/index, config digest, git rev) and
  ``split_runs`` is the reader that splits a multi-run file on those
  headers.

Beyond the shim surface, the sink carries the subsystem's event/counter
vocabulary: ``event(kind, **fields)`` for structured one-offs (compile
events at AOT points, watchdog stall diagnoses), ``gauge(name, value)``
for sampled quantities (queue depths, prefetch occupancy — mirrored into
the trace as Chrome counter tracks when tracing is on), and
``log_device_memory`` for per-device HBM occupancy via
``jax.local_devices()[*].memory_stats()``.

Timestamps: ``wall_s`` is seconds since THIS sink opened, measured on
``obs.trace.monotonic_s`` — the same clock the trace spans use, so a JSONL
record and a trace span at the same instant carry the same number (the
header records the absolute anchors for cross-run alignment).

jax is imported lazily (header fields only): the module must stay safe to
import from jax-free processes (the shm decode workers import the data
layer, which must never pull jax — data/shm_pipeline.py's contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import uuid
from typing import Any, Mapping

import numpy as np

from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


def scalarize(metrics: Mapping[str, Any]) -> tuple[dict[str, float], list[str]]:
    """metrics → (float scalars, names of non-castable drops).

    Non-finite values PASS THROUGH (the caller decides how loudly to
    announce them); only values ``float(np.asarray(v))`` cannot convert
    (arrays, strings, None) land in the drop list."""
    out: dict[str, float] = {}
    dropped: list[str] = []
    for k, v in metrics.items():
        try:
            out[k] = float(np.asarray(v))
        except (TypeError, ValueError):
            dropped.append(k)
    return out, dropped


def latency_percentiles(
    values_ms, ps: tuple[int, ...] = (50, 90, 99)
) -> dict[str, float]:
    """THE p50/p99 implementation (ISSUE 8 satellite): one summary shape
    shared by ``EventSink.histogram``, the serve ``LatencyStats`` snapshot
    and the obs/analyze span statistics, so their quantile semantics
    (numpy linear interpolation) can never drift.  Empty input → ``{}``
    (callers skip the record)."""
    arr = np.asarray(list(values_ms), dtype=np.float64)
    if arr.size == 0:
        return {}
    out: dict[str, float] = {"count": int(arr.size)}
    for p in ps:
        out[f"p{p}_ms"] = round(float(np.percentile(arr, p)), 3)
    out["mean_ms"] = round(float(arr.mean()), 3)
    out["max_ms"] = round(float(arr.max()), 3)
    return out


#: Serializes the parseable JSONL emit stream process-wide.  One lock for
#: EVERY emitter (fleet router, autoscaler, supervision CLI): the PR 16
#: interleaving fix — concurrent emitters must not interleave partial
#: lines, because downstream harnesses parse the stream as JSONL — now
#: lives in exactly one place, and also holds ACROSS subsystems sharing a
#: process (router + autoscaler), which per-object locks never did.
_EMIT_LOCK = make_lock("obs.events._EMIT_LOCK")


def emit_event(kind: str, *, sink=None, file=None, **fields) -> None:
    """THE structured-event emit layering (ISSUE 15/16, consolidated here
    by ISSUE 20): trace instant + guarded sink record + ONE serialized
    JSONL line on ``file`` (default stderr) per event.

    The output kwarg is named ``file`` (the ``print`` idiom) rather than
    ``stream`` because stream IS an event field (``fleet_stream_reaped``
    et al. carry the stream id) and ``**fields`` must be able to hold it.

    The sink write is best-effort — a broken sink must not mask the
    parseable line.  The line is built outside the lock and written with
    a single ``write`` call under it."""
    trace.instant(kind, **fields)
    if sink is not None:
        try:
            sink.event(kind, **fields)
        except Exception:
            pass  # a broken sink must not mask the parseable line
    out = file if file is not None else sys.stderr
    line = json.dumps({"event": kind, **fields}) + "\n"
    with _EMIT_LOCK:
        out.write(line)
        out.flush()


def _git_rev() -> str | None:
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout.strip() or None if r.returncode == 0 else None


def config_digest(config: Mapping[str, Any] | None) -> str | None:
    """Stable short digest of a run's config (argparse namespace dict):
    two runs in one directory are the same experiment iff digests match."""
    if config is None:
        return None
    blob = json.dumps(
        {k: config[k] for k in sorted(config)}, default=str, sort_keys=True
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _device_header_fields() -> dict[str, Any]:
    """device_kind/process fields for the run header — ONLY when jax is
    already loaded (never force a backend init from the logger).  As a
    side effect, publishes the resolved process index into the obs env
    contract: the sink is constructed AFTER distributed init and BEFORE
    the pipelines spawn their workers (train.py ordering), which is
    exactly the window where children can still inherit it."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    try:
        fields = {
            "device_kind": jax.devices()[0].device_kind,
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except Exception:
        return {}
    os.environ[trace.OBS_PINDEX_ENV] = str(fields["process_index"])
    return fields


class EventSink:
    """Process-0 structured sink: JSONL + stdout + optional TensorBoard.

    Surface-compatible superset of the old ``MetricLogger`` (``log``,
    ``close``); adds ``event``/``gauge``/``log_device_memory`` and writes
    the ``run_header`` record on open."""

    def __init__(
        self,
        log_dir: str | None,
        tensorboard: bool = False,
        stdout: bool = True,
        only_process_zero: bool = True,
        run_config: Mapping[str, Any] | None = None,
        filename: str = "metrics.jsonl",
    ):
        jax = sys.modules.get("jax")
        process_index = 0
        if jax is not None and only_process_zero:
            try:
                process_index = jax.process_index()
            except Exception:
                process_index = 0
        self._enabled = (not only_process_zero) or process_index == 0
        self._stdout = stdout
        self._jsonl = None
        # Serializes JSONL appends: the loop thread logs metrics while the
        # watchdog thread may write a stall event — interleaved partial
        # lines would corrupt both records.
        self._write_lock = make_lock("obs.events.EventSink._write_lock")
        self._tb = None
        self._t0 = trace.monotonic_s()
        self.run_id = uuid.uuid4().hex[:8]
        self.dropped_metrics_total = 0
        if not self._enabled:
            return
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, filename), "a")
            self._write(self._run_header(run_config))
            if tensorboard:
                try:
                    import tensorflow as tf  # heavyweight; only on request

                    self._tb = tf.summary.create_file_writer(
                        os.path.join(log_dir, "tb")
                    )
                except ImportError:
                    self._tb = None

    def _run_header(self, run_config) -> dict:
        rec = {
            "event": "run_header",
            "run_id": self.run_id,
            "t_wall": round(trace.to_wall(self._t0), 3),
            "argv": sys.argv,
            "config_digest": config_digest(run_config),
            "git_rev": _git_rev(),
        }
        rec.update(_device_header_fields())
        return rec

    def _write(self, rec: dict) -> None:
        with self._write_lock:
            if self._jsonl:
                self._jsonl.write(json.dumps(rec) + "\n")
                self._jsonl.flush()

    # ---- the MetricLogger surface ---------------------------------------

    def log(self, step: int, metrics: Mapping[str, Any], prefix: str = "train") -> None:
        if not self._enabled:
            return
        scalars, dropped = scalarize(metrics)
        nonfinite = {k: v for k, v in scalars.items() if not np.isfinite(v)}
        if self._jsonl:
            rec = {
                "step": step,
                "wall_s": round(trace.monotonic_s() - self._t0, 3),
            }
            rec.update({f"{prefix}/{k}": v for k, v in scalars.items()})
            if dropped:
                self.dropped_metrics_total += len(dropped)
                rec["dropped_metrics"] = sorted(dropped)
            self._write(rec)
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                for k, v in scalars.items():
                    # Non-finite points poison TB's scalar charts (the whole
                    # series renders empty); the JSONL + stdout announcement
                    # above carry the NaN, TB keeps the readable curve.
                    if np.isfinite(v):
                        tf.summary.scalar(f"{prefix}/{k}", v, step=step)
            self._tb.flush()
        if self._stdout:
            parts = " ".join(f"{k}={v:.4g}" for k, v in sorted(scalars.items()))
            print(f"[{prefix} step {step}] {parts}", flush=True)
        if nonfinite:
            # The single most important value a run logs (a NaN loss) must
            # never be silent: one unmissable line per occurrence, on top
            # of the record above (the loop's sanitizer aborts separately).
            print(
                f"!! NON-FINITE metrics at {prefix} step {step}: "
                + ", ".join(f"{k}={v}" for k, v in sorted(nonfinite.items())),
                flush=True,
            )

    def close(self) -> None:
        with self._write_lock:  # a mid-write close must not race the file
            if self._jsonl:
                self._jsonl.close()
                self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    # ---- the event/counter vocabulary -----------------------------------

    def event(self, kind: str, **fields: Any) -> None:
        """One structured record (compile events, watchdog diagnoses...):
        JSONL-only — events are machine food, not stdout chatter."""
        if not self._enabled or not self._jsonl:
            return
        rec = {
            "event": kind,
            "wall_s": round(trace.monotonic_s() - self._t0, 3),
        }
        rec.update(fields)
        self._write(rec)

    def gauge(self, name: str, value: float, step: int | None = None) -> None:
        """A sampled quantity (queue depth, occupancy): JSONL record plus a
        Chrome counter track when tracing is enabled."""
        trace.counter(name, value)
        if not self._enabled or not self._jsonl:
            return
        rec = {
            "event": "gauge",
            "wall_s": round(trace.monotonic_s() - self._t0, 3),
            "name": name,
            "value": float(value),
        }
        if step is not None:
            rec["step"] = step
        self._write(rec)

    def histogram(
        self, name: str, values_ms, step: int | None = None
    ) -> None:
        """One latency-distribution record: p50/p90/p99/mean/max over a
        window of millisecond samples (the serve frontend's per-window
        request latencies; any bounded sample list works).  Quantiles are
        computed here — the sink is the cold path — so callers just hand
        over the raw window; the math is ``latency_percentiles``, shared
        with the serve stats and the obs/analyze span statistics."""
        if not self._enabled or not self._jsonl:
            return
        summary = latency_percentiles(values_ms)
        if not summary:
            return
        rec = {
            "event": "histogram",
            "wall_s": round(trace.monotonic_s() - self._t0, 3),
            "name": name,
        }
        rec.update(summary)
        if step is not None:
            rec["step"] = step
        self._write(rec)

    def log_device_memory(self, step: int | None = None) -> None:
        """Per-device memory occupancy via ``memory_stats()`` (TPU/GPU
        backends; CPU returns nothing and this is a silent no-op)."""
        for name, value in device_memory_stats():
            self.gauge(name, value, step=step)


def device_memory_stats() -> list[tuple[str, float]]:
    """[(gauge_name, bytes)] from every local device's ``memory_stats()``
    — empty when jax isn't loaded or the backend doesn't report (CPU)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    out: list[tuple[str, float]] = []
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                if key in stats:
                    out.append((f"dev{d.id}.{key}", float(stats[key])))
    except Exception:
        return []
    return out


def split_runs(path: str) -> list[dict]:
    """Read a (possibly multi-run, append-mode) metrics JSONL file back as
    ``[{"header": dict | None, "records": [dict, ...]}, ...]``.

    Runs are delimited by ``run_header`` records; lines before the first
    header (pre-ISSUE-3 files) form a leading run with ``header=None``.
    Bare ``NaN``/``Infinity`` tokens (the Python ``json`` writer's
    non-finite encoding) parse back as floats; unparseable lines are
    collected under ``"corrupt"`` rather than raising — a half-written
    tail must not make the whole history unreadable."""
    runs: list[dict] = []
    current: dict | None = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if current is None:
                    current = {"header": None, "records": [], "corrupt": []}
                    runs.append(current)
                current.setdefault("corrupt", []).append(line)
                continue
            if isinstance(rec, dict) and rec.get("event") == "run_header":
                current = {"header": rec, "records": []}
                runs.append(current)
                continue
            if current is None:
                current = {"header": None, "records": []}
                runs.append(current)
            current["records"].append(rec)
    return runs


def metric_records(run: dict) -> list[dict]:
    """A run's step-metric records only (drops gauges/events): the shape
    pre-ISSUE-3 readers assumed the whole file had."""
    return [r for r in run["records"] if "step" in r and "event" not in r]
