"""Live telemetry plane: the in-process metrics registry + exposition.

The READ-NOW half of the observability subsystem (ISSUE 9).  PR 3 made
runs explainable after the fact (trace spans, events JSONL, watchdog
post-mortems) and PR 7 made those artifacts self-interpreting — but
nothing could answer "is this process healthy, and how loaded is it,
*right now*".  This module is that surface: a lock-light registry the
existing instrumentation feeds, a Prometheus-text exposition encoder, a
snapshot API the SLO monitor (obs/slo.py) evaluates rules on, and a
drain-safe stdlib HTTP status server (`train.py --obs-port`; the serve
frontend mounts the same payloads on its own port as ``GET /metrics`` /
``GET /healthz``).  The ROADMAP's serve-fleet router consumes exactly
this read surface for per-replica load and health.

Design constraints, in priority order (the obs/ house rules):

1. **Nil disabled-path overhead.**  Hot-path *push* sites
   (``record_train_window``, ``record_compile``, ``Counter.inc`` ...)
   check ONE module-level bool and return; no allocation, no lock, no
   clock read while telemetry is off.  Most of the registry is *pull*:
   gauges/histograms take a callback evaluated only at scrape time, so
   wiring the serve stats or watchdog ages in costs the hot path nothing
   at all (the scrape itself is the opt-in).
2. **No jax import.**  Device memory is read through
   ``obs.events.device_memory_stats`` (lazy — reports nothing until jax
   is already loaded); everything else is stdlib + numpy.  The module
   stays importable from jax-free processes.
3. **Read-only.**  Telemetry observes; it never alters numerics, queue
   behavior, or scheduling (PARITY.md).  The /healthz verdict comes from
   the watchdog registry's read-only probe — it cannot trip the
   one-dump-per-stall latch the poll thread owns.

Clock: ``obs.trace.monotonic_s`` (THE clock), so ages/uptimes are
comparable against span and heartbeat timestamps.

Exposition: the Prometheus text format (``text/plain; version=0.0.4``).
Windowed histograms are encoded as *summary* families (quantile series
from ``obs.events.latency_percentiles`` — one quantile implementation
repo-wide) plus ``_count``/``_sum`` over the window; counters and gauges
are the plain families.  ``parse_exposition`` is the matching reader the
bench consistency check and the smoke's schema check use.
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Any, Callable, Iterable, Iterator, Mapping

from batchai_retinanet_horovod_coco_tpu.obs import watchdog
from batchai_retinanet_horovod_coco_tpu.obs.events import (
    device_memory_stats,
    latency_percentiles,
)
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Module-level push gate: with telemetry off, every record site is ONE
# bool check (the trace-span discipline; tests pin this structurally).
_enabled = False


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the push-path record sites on (``--obs-port`` / tests).  Pull
    collectors never need this — scraping is its own opt-in."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


# Histogram-summary key translation: latency_percentiles' dict keys →
# (snapshot suffix, Prometheus quantile label).
_PCT_KEYS = (("p50_ms", "p50", "0.5"), ("p90_ms", "p90", "0.9"),
             ("p99_ms", "p99", "0.99"))


def _labels_key(labels: Mapping[str, str] | None) -> tuple[tuple[str, str], ...]:
    if not labels:
        return ()
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Metric:
    """Base: one named family.  Subclasses implement ``samples()`` →
    ``[(labels_tuple, value)]`` evaluated at scrape time."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help

    def samples(self) -> list[tuple[tuple[tuple[str, str], ...], float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonic cumulative count; optionally labeled children.

    ``inc()`` is gated on the module enable bool, then one lock-guarded
    float add (the lock covers exactly that add — "lock-light").
    """

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._lock = make_lock("obs.telemetry.Counter._lock")
        self._values: dict[tuple[tuple[str, str], ...], float] = {}

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if not _enabled:
            return
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_labels_key(labels), 0.0)

    def samples(self):
        with self._lock:
            return sorted(self._values.items())


class Gauge(Metric):
    """A sampled quantity: ``set()`` (push, enable-gated) or ``fn``
    (pull — evaluated only at scrape; zero hot-path cost)."""

    kind = "gauge"

    def __init__(
        self,
        name: str,
        help: str = "",
        fn: Callable[[], float] | None = None,
    ):
        super().__init__(name, help)
        self._lock = make_lock("obs.telemetry.Gauge._lock")
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._fn = fn

    def set(self, value: float, **labels: str) -> None:
        if not _enabled:
            return
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = float(value)

    def samples(self):
        if self._fn is not None:
            try:
                return [((), float(self._fn()))]
            except Exception:
                return []  # a dead callback must not kill the scrape
        with self._lock:
            return sorted(self._values.items())


class Histogram(Metric):
    """A windowed latency distribution, exposed as a Prometheus summary.

    Quantiles come from ``obs.events.latency_percentiles`` (THE p50/p99
    implementation) over either a push window (``observe()``, bounded,
    newest-wins) or a pull ``source`` callback returning the raw window
    in milliseconds (the serve frontend hands ``LatencyStats.window_ms``
    straight in — scrape-time pull, no new hot-path work).
    """

    kind = "summary"

    def __init__(
        self,
        name: str,
        help: str = "",
        window: int = 4096,
        source: Callable[[], Iterable[float]] | None = None,
    ):
        super().__init__(name, help)
        self._lock = make_lock("obs.telemetry.Histogram._lock")
        self._window = max(16, int(window))
        self._values: list[float] = []
        self._source = source

    def observe(self, value_ms: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._values.append(float(value_ms))
            if len(self._values) > self._window:
                del self._values[: -self._window]

    def window_ms(self) -> list[float]:
        if self._source is not None:
            try:
                return [float(v) for v in self._source()]
            except Exception:
                return []
        with self._lock:
            return list(self._values)

    def summary(self) -> dict[str, float]:
        """{count, p50, p90, p99, mean, max, sum} over the window
        (empty window → {}); the snapshot/exposition payload."""
        values = self.window_ms()
        pct = latency_percentiles(values)
        if not pct:
            return {}
        out = {"count": float(pct["count"]), "sum": round(sum(values), 3)}
        for src, dst, _q in _PCT_KEYS:
            out[dst] = pct[src]
        out["mean"] = pct["mean_ms"]
        out["max"] = pct["max_ms"]
        return out

    def samples(self):  # quantile series (exposition assembles the rest)
        out = []
        summary = self.summary()
        for _src, dst, q in _PCT_KEYS:
            if dst in summary:
                out.append(((("quantile", q),), summary[dst]))
        return out


#: One scrape-time sample from a collector callback:
#: (family name, kind, help, labels dict | None, value).
CollectorSample = tuple[str, str, str, Mapping[str, str] | None, float]


class Registry:
    """Named metrics + scrape-time collector callbacks.

    ``snapshot()`` (flat name→float dict, the SLO monitor's input) and
    ``prometheus_text()`` (the /metrics payload) are both views over the
    same ``collect()`` pass, so they can never disagree.
    """

    def __init__(self):
        self._lock = make_lock("obs.telemetry.Registry._lock")
        self._metrics: dict[str, Metric] = {}
        self._collectors: list[Callable[[], Iterable[CollectorSample]]] = []

    # ---- registration ----------------------------------------------------

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            have = self._metrics.get(metric.name)
            if have is not None:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def _get_or_create(self, cls, name: str, **kwargs) -> Metric:
        with self._lock:
            have = self._metrics.get(name)
            if have is not None:
                if type(have) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(have).__name__}, not {cls.__name__}"
                    )
                return have
            m = self._metrics[name] = cls(name, **kwargs)
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(
        self, name: str, help: str = "",
        fn: Callable[[], float] | None = None,
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help=help, fn=fn)

    def histogram(
        self, name: str, help: str = "", window: int = 4096,
        source: Callable[[], Iterable[float]] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help=help, window=window, source=source
        )

    def register_collector(
        self, fn: Callable[[], Iterable[CollectorSample]]
    ) -> None:
        """A scrape-time callback yielding ``CollectorSample`` tuples —
        the pull idiom for dynamic label sets (per-component watchdog
        ages, per-device memory) where fixed metric objects don't fit."""
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(
        self, fn: Callable[[], Iterable[CollectorSample]]
    ) -> None:
        """Drop a registered collector (identity match).  A component
        with a shorter lifetime than the registry it reports into (the
        fleet autoscaler on the router's registry, ISSUE 19) must detach
        on stop, or its gauges outlive it as frozen lies."""
        with self._lock:
            self._collectors = [c for c in self._collectors if c is not fn]

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    # ---- the one collect pass --------------------------------------------

    def collect(self) -> dict[str, dict]:
        """family name → {"kind", "help", "samples": [(labels, value)],
        "summary": {...} (histograms only)} — deterministically ordered."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        families: dict[str, dict] = {}
        for m in metrics:
            fam = families.setdefault(
                m.name, {"kind": m.kind, "help": m.help, "samples": []}
            )
            fam["samples"].extend(m.samples())
            if isinstance(m, Histogram):
                fam["summary"] = m.summary()
        for fn in collectors:
            try:
                samples = list(fn())
            except Exception:
                continue  # a dead collector must not kill the scrape
            for name, kind, help_text, labels, value in samples:
                fam = families.setdefault(
                    name, {"kind": kind, "help": help_text, "samples": []}
                )
                fam["samples"].append((_labels_key(labels), float(value)))
        for fam in families.values():
            fam["samples"].sort()
        return dict(sorted(families.items()))

    # ---- views -----------------------------------------------------------

    def snapshot(self) -> dict[str, float]:
        """Flat name → value (the SLO monitor's rule input).

        Labeled samples key as ``name{label="v",...}``; an aggregate
        lands under the bare name too (counters: sum; gauges: max — the
        alert-conservative fold for ages/depths) unless an unlabeled
        sample already owns it.  Histograms key their summary as
        ``name.count`` / ``name.p50`` / ``name.p99`` / ``name.mean`` /
        ``name.max``.
        """
        out: dict[str, float] = {}
        for name, fam in self.collect().items():
            if fam["kind"] == "summary":
                for k, v in fam.get("summary", {}).items():
                    if k != "sum":
                        out[f"{name}.{k}"] = v
                continue
            labeled = [(ls, v) for ls, v in fam["samples"] if ls]
            for ls, v in fam["samples"]:
                out[f"{name}{_fmt_labels(ls)}" if ls else name] = v
            if labeled and name not in out:
                vals = [v for _, v in labeled]
                out[name] = (
                    sum(vals) if fam["kind"] == "counter" else max(vals)
                )
        return out

    def prometheus_text(self) -> str:
        """The Prometheus text exposition (``text/plain; version=0.0.4``)."""
        lines: list[str] = []
        for name, fam in self.collect().items():
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for ls, v in fam["samples"]:
                lines.append(f"{name}{_fmt_labels(ls)} {_fmt_value(v)}")
            if fam["kind"] == "summary":
                s = fam.get("summary", {})
                lines.append(f"{name}_count {_fmt_value(s.get('count', 0))}")
                lines.append(f"{name}_sum {_fmt_value(s.get('sum', 0))}")
        return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?P<labels>\{.*\})?\s+"
    r"(?P<value>\S+)$"
)


def parse_exposition(text: str) -> tuple[dict[str, str], dict[str, float]]:
    """The matching reader for ``prometheus_text``: returns
    ``(types, samples)`` where ``types`` maps family name → TYPE and
    ``samples`` maps the raw sample key (``name`` or ``name{...}``) →
    float value.  Consumed by the bench consistency check and the
    telemetry smoke's schema check; unparseable lines are skipped (a
    schema check then fails on the MISSING family, loudly)."""
    types: dict[str, str] = {}
    samples: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        samples[m.group("name") + (m.group("labels") or "")] = value
    return types, samples


_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(v: str) -> str:
    return re.sub(
        r"\\(.)", lambda m: "\n" if m.group(1) == "n" else m.group(1), v
    )


def parse_exposition_samples(
    text: str,
) -> tuple[dict[str, str], list[tuple[str, dict[str, str], float]]]:
    """The STRUCTURED reader for ``prometheus_text``: ``(types,
    samples)`` where each sample is ``(family name, labels dict, value)``
    with label values unescaped.  This is the metrics-federation parse
    (ISSUE 15): the fleet router re-labels each replica's scraped series
    with ``replica=<id>`` before re-exposing them, which needs the labels
    as data, not as the raw brace string ``parse_exposition`` keeps."""
    types: dict[str, str] = {}
    samples: list[tuple[str, dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {
            k: _unescape_label_value(v)
            for k, v in _LABEL_PAIR_RE.findall(m.group("labels") or "")
        }
        samples.append((m.group("name"), labels, value))
    return types, samples


# ---------------------------------------------------------------------------
# Built-in collectors
# ---------------------------------------------------------------------------


def watchdog_collector(
    wd: watchdog.Watchdog | None = None,
) -> Callable[[], Iterator[CollectorSample]]:
    """Per-component heartbeat ages + the stall verdict from the (default)
    watchdog registry — the health half of the per-replica read surface."""

    def collect() -> Iterator[CollectorSample]:
        w = wd or watchdog.default()
        comps = w.components()
        stalled = w.stalled_components()
        yield (
            "watchdog_components", "gauge",
            "components registered with the stall watchdog", None,
            float(len(comps)),
        )
        yield (
            "watchdog_stalled", "gauge",
            "non-idle components currently past their stall budget "
            "(healthz flips 503 when > 0)", None, float(len(stalled)),
        )
        for name, age in sorted(comps.items()):
            yield (
                "watchdog_beat_age_seconds", "gauge",
                "seconds since each component's last heartbeat",
                {"component": name}, round(age, 3),
            )

    return collect


def device_memory_collector() -> Iterator[CollectorSample]:
    """Per-device HBM occupancy via the events helper (lazy jax: reports
    nothing until jax is loaded / on backends without memory_stats)."""
    for name, value in device_memory_stats():
        dev, _, kind = name.partition(".")
        yield (
            "device_memory_bytes", "gauge",
            "per-device memory occupancy from memory_stats()",
            {"device": dev, "kind": kind}, value,
        )


_START_T = monotonic_s()


def _process_collector() -> Iterator[CollectorSample]:
    yield (
        "process_uptime_seconds", "gauge",
        "seconds since the telemetry module loaded (monotonic)",
        None, round(monotonic_s() - _START_T, 3),
    )


# ---------------------------------------------------------------------------
# Checkpoint health (ISSUE 11, utils/checkpoint.py)
# ---------------------------------------------------------------------------

# Push half: the checkpoint writer records each landed save (enable-gated,
# one bool while off).  Pull half: staleness is computed at scrape time,
# so a WEDGED saver — the failure this exists for — keeps degrading with
# no further events; the built-in staleness SLO rule
# (obs/slo.py::ckpt_staleness_rule) fires on the STEP-based
# ckpt_staleness gauge > 2, i.e. training advanced 2x the measured
# steps-between-saves with no checkpoint landing.  (The wall-clock
# age gauges stay informational: a multi-minute sync eval inflates
# them while no step runs.)
_ckpt_lock = make_lock("obs.telemetry._ckpt_lock")
_ckpt_state = {
    "last_success_t": None,   # monotonic_s of the last landed save
    "interval_s": None,       # gap between the last two landed saves
    "last_save_s": None,      # write duration of the last landed save
    "last_bytes": None,
    "last_step": None,        # step of the last landed save
    "interval_steps": None,   # steps between the last two landed saves
    "saves_total": 0,
    "inflight": 0,
}


def record_ckpt_save(step: int, save_s: float, total_bytes: int) -> None:
    """The checkpoint writer's landed-save record site.  One bool check
    while telemetry is off."""
    if not _enabled:
        return
    now = monotonic_s()
    with _ckpt_lock:
        prev = _ckpt_state["last_success_t"]
        if prev is not None:
            _ckpt_state["interval_s"] = now - prev
        prev_step = _ckpt_state["last_step"]
        if prev_step is not None and step > prev_step:
            _ckpt_state["interval_steps"] = int(step) - int(prev_step)
        _ckpt_state["last_success_t"] = now
        _ckpt_state["last_save_s"] = float(save_s)
        _ckpt_state["last_bytes"] = float(total_bytes)
        _ckpt_state["last_step"] = int(step)
        _ckpt_state["saves_total"] += 1


def record_ckpt_inflight(n: int) -> None:
    """Writer-queue occupancy (0/1 under the one-behind contract)."""
    if not _enabled:
        return
    with _ckpt_lock:
        _ckpt_state["inflight"] = int(n)


def _ckpt_collector() -> Iterator[CollectorSample]:
    with _ckpt_lock:
        s = dict(_ckpt_state)
    if not s["saves_total"] and not s["inflight"]:
        return  # no checkpointing in this process — no metric noise
    yield (
        "ckpt_saves_total", "counter",
        "checkpoints successfully committed by this process", None,
        float(s["saves_total"]),
    )
    yield (
        "ckpt_inflight", "gauge",
        "checkpoint writes currently in flight (0/1: one-behind)", None,
        float(s["inflight"]),
    )
    if s["last_save_s"] is not None:
        yield (
            "ckpt_save_s", "gauge",
            "write seconds of the last committed checkpoint", None,
            round(s["last_save_s"], 4),
        )
    if s["last_step"] is not None:
        yield (
            "ckpt_last_step", "gauge",
            "train step of the last committed checkpoint (what resume "
            "would restore — the actionable half of a staleness page)",
            None, float(s["last_step"]),
        )
    if s["last_bytes"] is not None:
        yield (
            "ckpt_bytes", "gauge",
            "payload bytes of the last committed checkpoint", None,
            s["last_bytes"],
        )
    if s["last_success_t"] is not None:
        age = monotonic_s() - s["last_success_t"]
        yield (
            "ckpt_last_success_age_s", "gauge",
            "seconds since the last successfully committed checkpoint "
            "(informational: grows through legitimate pauses — evals, "
            "compiles — too)", None, round(age, 3),
        )
        if s["interval_s"] is not None and s["interval_s"] > 0:
            yield (
                "ckpt_age_over_interval", "gauge",
                "ckpt_last_success_age_s / measured save interval "
                "(informational; a long sync eval inflates it — the "
                "staleness SLO watches ckpt_staleness instead)", None,
                round(age / s["interval_s"], 3),
            )
        # The SLO-grade signal: STEPS since the last save over the
        # measured steps-between-saves.  Steps don't advance during
        # evals/compiles, so a healthy pause can't inflate it — > 2
        # genuinely means the loop is training past the save cadence
        # without checkpoints landing (wedged/dying saver).
        step_now = _current_train_step()
        if (
            step_now is not None
            and s["interval_steps"]
            and s["interval_steps"] > 0
        ):
            yield (
                "ckpt_staleness", "gauge",
                "(train_step - ckpt_last_step) / measured save interval "
                "in steps (> 2 = saves stopped landing while training "
                "advances; the built-in staleness SLO rule fires on it)",
                None,
                round(
                    max(0.0, step_now - s["last_step"])
                    / s["interval_steps"],
                    3,
                ),
            )


def _current_train_step() -> float | None:
    """The train_step gauge's last pushed value, if the loop has
    recorded one (scrape-time read; no lock beyond the gauge's own)."""
    if _train_gauges is None:
        return None
    samples = _train_gauges["step"].samples()
    return float(samples[0][1]) if samples else None


# ---------------------------------------------------------------------------
# The process-default registry + the train-loop record sites
# ---------------------------------------------------------------------------

_default: Registry | None = None
_default_lock = make_lock("obs.telemetry._default_lock")


def default() -> Registry:
    """The process-wide registry (train status server / --obs-port),
    preloaded with the watchdog, device-memory, and process collectors."""
    global _default
    with _default_lock:
        if _default is None:
            r = Registry()
            r.register_collector(watchdog_collector())
            r.register_collector(device_memory_collector)
            r.register_collector(_process_collector)
            r.register_collector(_ckpt_collector)
            _default = r
        return _default


def reset() -> None:
    """Test hook: disable and drop the default registry + train/serve
    handles."""
    global _default, _train_gauges, _serve_metrics
    disable()
    with _default_lock:
        _default = None
    _train_gauges = None
    _serve_metrics = None
    with _ckpt_lock:
        _ckpt_state.update(
            last_success_t=None, interval_s=None, last_save_s=None,
            last_bytes=None, last_step=None, interval_steps=None,
            saves_total=0, inflight=0,
        )


# Lazily-created train metric handles on the default registry (the loop's
# record sites must not pay registration on the disabled path).
_train_gauges: dict[str, Any] | None = None


def _train_handles() -> dict[str, Any]:
    global _train_gauges
    if _train_gauges is None:
        r = default()
        _train_gauges = {
            "step": r.gauge("train_step", "last completed train step"),
            "images_per_s": r.gauge(
                "train_images_per_sec", "window-averaged images/sec"
            ),
            "step_time_ms": r.gauge(
                "train_step_time_ms", "window-averaged wall ms per step"
            ),
            "data_wait_ms": r.gauge(
                "train_data_wait_ms",
                "window-averaged ms/step the host blocked on input",
            ),
            "data_wait_fraction": r.gauge(
                "train_data_wait_fraction",
                "data_wait_ms / step_time_ms over the last window",
            ),
            "compiles": r.counter(
                "train_compiles_total", "train-step compiles by bucket"
            ),
            "last_compile_s": r.gauge(
                "train_last_compile_s", "build seconds of the last compile"
            ),
            # Numerics plane (ISSUE 10, obs/numerics.py): the SLO
            # monitor's built-in nonfinite + grad-norm-spike rules
            # evaluate these.
            "grad_norm": r.gauge(
                "train_grad_norm",
                "pre-clip global gradient norm at the last log window",
            ),
            "update_ratio": r.gauge(
                "train_update_ratio",
                "update-norm / param-norm at the last log window",
            ),
            "replica_agreement": r.gauge(
                "train_replica_agreement",
                "min/max ratio of per-replica local grad norms "
                "(1 = replicas agree; collapsing = silent desync)",
            ),
            "nonfinite": r.counter(
                "train_nonfinite_total",
                "non-finite gradient elements observed + tripped "
                "finite-checks (any increase is an incident)",
            ),
            # Comm subsystem / gradient-compression health (ISSUE 13,
            # comm/compress.py): the always-armed ef_residual_spike SLO
            # rule evaluates train_ef_residual.
            "ef_residual": r.gauge(
                "train_ef_residual",
                "global L2 norm of the gradient-compression error-"
                "feedback residual at the last log window",
            ),
            "ef_saturation": r.gauge(
                "train_ef_saturation",
                "fraction of quantized elements at the int8 clip "
                "boundary (per-block scale saturation)",
            ),
            "comm_bytes": r.counter(
                "train_comm_compressed_bytes_total",
                "cumulative compressed gradient bytes-on-wire "
                "(per-device ring estimate, comm/compress plan)",
            ),
            # Per-hop wire accounting (ISSUE 16, hierarchical tree):
            # the DCN counter is the scarce-fabric spend the headline
            # ratio is stated against; ICI stays exact (f32) but its
            # bytes are counted so the split always sums to the total.
            # The DCN-labeled residual gauge is what the per-hop
            # ef_residual_spike rule (hop="dcn") evaluates.
            "comm_ici_bytes": r.counter(
                "train_comm_ici_bytes_total",
                "cumulative gradient bytes-on-wire over the fast "
                "intra-slice (ICI) hops of the hierarchical tree "
                "(exact f32 by construction)",
            ),
            "comm_dcn_bytes": r.counter(
                "train_comm_dcn_bytes_total",
                "cumulative gradient bytes-on-wire over the slow "
                "cross-slice (DCN) hop of the hierarchical tree "
                "(the compressed exchange)",
            ),
            "ef_residual_dcn": r.gauge(
                "train_ef_residual_dcn",
                "global L2 norm of the DCN-hop error-feedback "
                "residual (hierarchical tree; the only hop that "
                "quantizes)",
            ),
        }
    return _train_gauges


def record_train_window(
    step: int,
    images_per_s: float,
    step_time_ms: float,
    data_wait_ms: float,
) -> None:
    """The train loop's per-log-window record site (train/loop.py).  One
    bool check while telemetry is off."""
    if not _enabled:
        return
    g = _train_handles()
    g["step"].set(step)
    g["images_per_s"].set(images_per_s)
    g["step_time_ms"].set(step_time_ms)
    g["data_wait_ms"].set(data_wait_ms)
    g["data_wait_fraction"].set(
        data_wait_ms / step_time_ms if step_time_ms > 0 else 0.0
    )


def record_compile(bucket: str, build_s: float) -> None:
    """The train loop's compile-point record site.  One bool check off."""
    if not _enabled:
        return
    g = _train_handles()
    g["compiles"].inc(bucket=bucket)
    g["last_compile_s"].set(round(build_s, 3))


def record_numerics(
    grad_norm: float | None = None,
    update_ratio: float | None = None,
    nonfinite: float | None = None,
    replica_agreement: float | None = None,
) -> None:
    """The train loop's numerics record site (ISSUE 10; per log window —
    the ``train_step`` gauge from ``record_train_window`` at the same
    call site carries the step).  One bool check while telemetry is off;
    absent fields (summary disabled, single-device run) are skipped."""
    if not _enabled:
        return
    g = _train_handles()
    if grad_norm is not None and math.isfinite(grad_norm):
        g["grad_norm"].set(float(grad_norm))
    if update_ratio is not None and math.isfinite(update_ratio):
        g["update_ratio"].set(float(update_ratio))
    if replica_agreement is not None and math.isfinite(replica_agreement):
        g["replica_agreement"].set(float(replica_agreement))
    if nonfinite is not None and (
        not math.isfinite(nonfinite) or nonfinite > 0
    ):
        # A non-finite COUNT that is itself non-finite means the summary
        # was poisoned — count it as one incident rather than losing it.
        g["nonfinite"].inc(
            float(nonfinite) if math.isfinite(nonfinite) else 1.0
        )


def record_comm(
    ef_residual: float | None = None,
    ef_saturation: float | None = None,
    compressed_bytes: float | None = None,
    ici_bytes: float | None = None,
    dcn_bytes: float | None = None,
    ef_residual_dcn: float | None = None,
    steps: int = 1,
) -> None:
    """The train loop's comm/EF record site (ISSUE 13/16; per log
    window).  One bool check while telemetry is off; absent fields
    (compression off, EF off, flat tree) are skipped.  The byte figures
    are the plan's static per-step numbers — the counters accumulate
    them over the window's ``steps``.  ``ici_bytes`` / ``dcn_bytes`` /
    ``ef_residual_dcn`` exist only on hierarchical-topology runs
    (per-hop accounting)."""
    if not _enabled:
        return
    g = _train_handles()
    if ef_residual is not None and math.isfinite(ef_residual):
        g["ef_residual"].set(float(ef_residual))
    if ef_saturation is not None and math.isfinite(ef_saturation):
        g["ef_saturation"].set(float(ef_saturation))
    if compressed_bytes is not None and math.isfinite(compressed_bytes):
        g["comm_bytes"].inc(float(compressed_bytes) * max(1, int(steps)))
    if ici_bytes is not None and math.isfinite(ici_bytes):
        g["comm_ici_bytes"].inc(float(ici_bytes) * max(1, int(steps)))
    if dcn_bytes is not None and math.isfinite(dcn_bytes):
        g["comm_dcn_bytes"].inc(float(dcn_bytes) * max(1, int(steps)))
    if ef_residual_dcn is not None and math.isfinite(ef_residual_dcn):
        g["ef_residual_dcn"].set(float(ef_residual_dcn))


_serve_metrics: dict[str, Any] | None = None


def _serve_handles() -> dict[str, Any]:
    """Lazily-created serve batching handles on the default registry
    (ISSUE 14) — like the train handles, registration is never paid on
    the disabled path."""
    global _serve_metrics
    if _serve_metrics is None:
        r = default()
        _serve_metrics = {
            "occupancy": r.histogram(
                "serve_batch_occupancy",
                "per-dispatched-batch device occupancy "
                "(live rows / padded batch size)",
            ),
            "free_slots": r.gauge(
                "serve_free_slots",
                "unclaimed slots across the assembling batches at the "
                "last dispatch (idle device capacity)",
            ),
            "slot_wait": r.histogram(
                "serve_slot_wait_ms",
                "ms a claimed slot waited between claim and seal "
                "(continuous in-flight batching admission latency)",
            ),
        }
    return _serve_metrics


def record_serve_batch(
    occupancy: float,
    free_slots: float,
    slot_wait_ms=(),
) -> None:
    """The serve frontend's per-dispatched-batch record site (ISSUE 14;
    serve/frontend.py ``_on_batch``).  One bool check while telemetry is
    off."""
    if not _enabled:
        return
    g = _serve_handles()
    if math.isfinite(occupancy):
        g["occupancy"].observe(float(occupancy))
    if math.isfinite(free_slots):
        g["free_slots"].set(float(free_slots))
    for w in slot_wait_ms:
        if math.isfinite(w):
            g["slot_wait"].observe(float(w))


_stream_metrics: dict[str, Any] | None = None


def _stream_handles() -> dict[str, Any]:
    """Lazily-created streaming-detection handles on the default registry
    (ISSUE 18) — the ``_serve_handles`` pattern: registration is never
    paid on the disabled path."""
    global _stream_metrics
    if _stream_metrics is None:
        r = default()
        _stream_metrics = {
            "hits": r.counter(
                "serve_stream_cache_hits_total",
                "frames short-circuited by the frame-delta cache",
            ),
            "misses": r.counter(
                "serve_stream_cache_misses_total",
                "frames dispatched to the device",
            ),
            "latency": r.histogram(
                "serve_stream_frame_latency_ms",
                "per-frame submit→deliver latency across all streams",
            ),
        }
    return _stream_metrics


def record_stream_frame(cache_hit: bool, latency_ms: float) -> None:
    """The stream delivery thread's per-frame record site (ISSUE 18;
    serve/stream.py ``_finish``).  One bool check while telemetry is
    off."""
    if not _enabled:
        return
    g = _stream_handles()
    (g["hits"] if cache_hit else g["misses"]).inc()
    if math.isfinite(latency_ms):
        g["latency"].observe(float(latency_ms))


def record_nonfinite_trip(metric: str) -> None:
    """The loop's abort-path record site: a tripped finite-check counts
    into ``train_nonfinite_total`` (labeled by the tripped metric) so the
    built-in nonfinite SLO rule fires even when the in-step summary was
    off.  One bool check while telemetry is off."""
    if not _enabled:
        return
    _train_handles()["nonfinite"].inc(metric=metric)


# ---------------------------------------------------------------------------
# Health
# ---------------------------------------------------------------------------


def healthz(wd: watchdog.Watchdog | None = None) -> tuple[int, dict]:
    """(status_code, payload) for a truthful liveness endpoint: 200 while
    every non-idle watchdog component is within its stall budget, 503
    naming the most-stalled component otherwise.  Read-only — it never
    touches the watchdog's one-dump-per-stall latch."""
    w = wd or watchdog.default()
    stalled = w.stalled_components()
    components = {n: round(a, 3) for n, a in sorted(w.components().items())}
    if stalled:
        return 503, {
            "status": "stalled",
            "component": stalled[0]["component"],
            "stalled": stalled,
            "components": components,
        }
    return 200, {"status": "ok", "components": components}


# ---------------------------------------------------------------------------
# The stdlib HTTP status server (train.py --obs-port)
# ---------------------------------------------------------------------------


class StatusServer:
    """A drain-safe stdlib HTTP status server over one registry.

    GET /metrics  → Prometheus text exposition (the scrape target)
    GET /healthz  → watchdog-backed liveness (200 ok / 503 + component)
    GET /statusz  → the full JSON snapshot (humans + the fleet router)

    Drain safety (the pod-exit contract): the listener thread is a
    daemon, per-request handler threads are daemons, ``close()`` bounds
    its join and is idempotent — a wedged scraper can never hold a pod
    exit hostage.  The listener registers with the stall watchdog and
    parks idle (liveness is witnessed per request), so watchdog-coverage
    passes non-vacuously without false stall dumps.
    """

    def __init__(
        self,
        registry: Registry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        wd: watchdog.Watchdog | None = None,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = registry if registry is not None else default()
        self.registry = registry
        self._wd = wd
        self._error: BaseException | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                if self.path == "/metrics":
                    self._send(
                        200,
                        registry.prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/healthz":
                    code, payload = healthz(outer._wd)
                    self._send(
                        code, json.dumps(payload).encode(), "application/json"
                    )
                elif self.path in ("/statusz", "/vars"):
                    self._send(
                        200,
                        json.dumps(
                            registry.snapshot(), sort_keys=True
                        ).encode(),
                        "application/json",
                    )
                else:
                    self._send(
                        404, b'{"error": "not_found"}', "application/json"
                    )

            def log_message(self, *args) -> None:
                pass  # scrape traffic is not stdout's business

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True  # handlers can't block pod exit
        self._thread: threading.Thread | None = None
        self._hb: watchdog.Heartbeat | None = None
        self._closed = False

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    def _run(self) -> None:
        try:
            self._httpd.serve_forever(poll_interval=0.2)
        except BaseException as e:
            # Crash channel (thread-error-contract): a dead status server
            # must be visible — stored for close() to re-raise, announced
            # on stderr either way (nobody may ever call close()).
            self._error = e
            import sys

            print(
                json.dumps(
                    {"event": "telemetry_server_crashed", "error": repr(e)}
                ),
                file=sys.stderr, flush=True,
            )
            raise

    def start(self) -> "StatusServer":
        if self._thread is not None:
            return self
        # Registered but immediately idle: the listener legitimately
        # sleeps between scrapes; a wedged HTTP stack shows up as the
        # scraper's timeout, not as a false stall dump.
        self._hb = watchdog.register("obs-telemetry-http")
        self._hb.idle()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-telemetry-http"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Bounded, idempotent teardown.  A listener crash was already
        announced on stderr at crash time (the crash channel); close()
        re-announces as a warning rather than raising — telemetry is
        read-only, and a dead scrape endpoint must never turn a
        successful run into a failed pod exit."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            # shutdown() blocks on serve_forever()'s exit handshake —
            # calling it on a never-started server would wait forever.
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._hb is not None:
            self._hb.close()
            self._hb = None
        if self._error is not None:
            import warnings

            warnings.warn(
                f"telemetry status server crashed mid-run: {self._error!r}"
            )

    def __enter__(self) -> "StatusServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def start_http_server(
    registry: Registry | None = None,
    port: int = 0,
    host: str = "127.0.0.1",
) -> StatusServer:
    """Convenience bring-up: construct + start a ``StatusServer`` (the
    ``--obs-port`` path; port 0 binds an ephemeral port, read it back
    from ``.port``)."""
    return StatusServer(registry, host=host, port=port).start()
