"""Stall watchdog: a heartbeat registry for every long-lived thread/process.

The failure mode this exists for (ISSUE 3): the system now runs four
independent concurrent machines — shm decode workers, the device-prefetch
thread, the eval consumer, async mid-training eval — and when one wedges,
today's only signal is a generic ``worker_timeout`` RuntimeError (shm
pipeline) or a silently hung run (everything else).  The watchdog converts
that into an attributable diagnosis BEFORE the timeout kills the run: which
component stopped heartbeating, for how long, what every other component
was doing (last beat + its own details: queue depths, in-flight counts),
and a ``faulthandler`` dump of every Python thread's stack — the
py-spy-style evidence that turns "it hung" into a file/line.

Contract:

- ``register(name)`` → a ``Heartbeat``; the component calls ``beat()`` on
  every unit of progress (one attribute store — safe on any hot path) and
  ``close()`` on exit.  Names are uniquified (``name#2``) so repeated
  evals re-registering the same component never collide.
- ``beat()`` also re-arms the stall detector; one stall produces ONE dump
  until the component beats again (no log spam while wedged).
- ``idle()`` marks a component as legitimately quiescent (blocked on
  backpressure — a full output queue — or waiting between evals); idle
  components are listed in diagnoses but never flagged.  The next
  ``beat()`` clears it.
- The watchdog only OBSERVES.  It never kills anything: the existing
  timeouts (``PipelineConfig.worker_timeout``, collective deadlines)
  remain the executioners; the watchdog's job is that when they fire, the
  post-mortem is already on disk.
- Registration is always allowed and costs one dict insert; the poll
  thread only exists between ``start()``/``stop()`` — an un-started
  watchdog is a passive registry with nil overhead.

The shm decode workers do NOT register: they already heartbeat implicitly
through the result queue, so the coordinator's own ``shm-pipe-coordinator``
component carries the fleet's liveness (it beats on every arriving worker
result — a wedged/dead fleet stops that heartbeat within one task, and its
details report ``workers_alive``).  ``scripts/audit_threads.py`` statically
enforces that every thread/process spawn site in the package either
registers or carries an explicit ``# watchdog`` comment naming its story.
"""

from __future__ import annotations

import faulthandler
import json
import sys
import threading
from typing import Any, Callable

from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.utils.locks import make_lock


class _Component:
    __slots__ = ("name", "stall_after", "details", "last_beat", "idle", "warned")

    def __init__(
        self,
        name: str,
        stall_after: float | None,
        details: Callable[[], dict] | None,
    ):
        self.name = name
        self.stall_after = stall_after  # None = watchdog default
        self.details = details
        self.last_beat = monotonic_s()
        self.idle = False
        self.warned = False


class Heartbeat:
    """The component-side handle.  ``beat()`` is one float store + two bool
    stores — call it as often as you like."""

    __slots__ = ("_c", "_registry")

    def __init__(self, component: _Component, registry: "Watchdog"):
        self._c = component
        self._registry = registry

    def beat(self) -> None:
        c = self._c
        c.last_beat = monotonic_s()
        c.idle = False
        c.warned = False

    def idle(self) -> None:
        """Declare legitimate quiescence (backpressure/waiting): skipped by
        the stall check until the next ``beat()``."""
        self._c.idle = True

    def close(self) -> None:
        self._registry._unregister(self._c)

    @property
    def name(self) -> str:
        return self._c.name


class Watchdog:
    """The registry + (optional) poll thread.  Module-level helpers below
    proxy a process-wide default instance; tests construct their own."""

    def __init__(
        self,
        stall_after: float = 120.0,
        poll_interval: float = 5.0,
        dump_path: str | None = None,
        on_stall: Callable[[dict], None] | None = None,
        sink: Any | None = None,
    ):
        self.stall_after = stall_after
        self.poll_interval = poll_interval
        self.dump_path = dump_path
        self.on_stall = on_stall
        self.sink = sink  # an obs.events.EventSink (or None)
        self._lock = make_lock("obs.watchdog.Watchdog._lock")
        self._components: dict[str, _Component] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- registry --------------------------------------------------------

    def register(
        self,
        name: str,
        stall_after: float | None = None,
        details: Callable[[], dict] | None = None,
    ) -> Heartbeat:
        with self._lock:
            unique = name
            n = 2
            while unique in self._components:
                unique = f"{name}#{n}"
                n += 1
            c = _Component(unique, stall_after, details)
            self._components[unique] = c
        return Heartbeat(c, self)

    def _unregister(self, c: _Component) -> None:
        with self._lock:
            self._components.pop(c.name, None)

    def components(self) -> dict[str, float]:
        """name → seconds since last beat (diagnostics/tests)."""
        now = monotonic_s()
        with self._lock:
            return {n: now - c.last_beat for n, c in self._components.items()}

    def stalled_components(self, now: float | None = None) -> list[dict]:
        """READ-ONLY stall probe (the telemetry /healthz verdict,
        obs/telemetry.py): every non-idle component currently past its
        stall budget, most-stalled first.  Unlike ``check_once`` it never
        touches the one-dump-per-stall ``warned`` latch — a health scrape
        must not eat the poll thread's diagnosis."""
        now = monotonic_s() if now is None else now
        with self._lock:
            comps = list(self._components.values())
        out = []
        for c in comps:
            if c.idle:
                continue
            budget = c.stall_after or self.stall_after
            age = now - c.last_beat
            if age > budget:
                out.append(
                    {
                        "component": c.name,
                        "stalled_for_s": round(age, 3),
                        "stall_after_s": budget,
                    }
                )
        out.sort(key=lambda d: (-d["stalled_for_s"], d["component"]))
        return out

    # ---- stall detection -------------------------------------------------

    def _snapshot(self, now: float) -> list[dict]:
        with self._lock:
            comps = list(self._components.values())
        snap = []
        for c in comps:
            details = None
            if c.details is not None:
                try:
                    details = c.details()
                except Exception as e:  # a dead component's gauge must not
                    details = {"details_error": repr(e)}  # kill the dump
            snap.append(
                {
                    "name": c.name,
                    "age_s": round(now - c.last_beat, 3),
                    "idle": c.idle,
                    "stall_after_s": c.stall_after or self.stall_after,
                    "details": details,
                }
            )
        return snap

    def check_once(self, now: float | None = None) -> dict | None:
        """One poll: returns a diagnosis dict if any non-idle component
        exceeded its stall budget (the most-stalled one is named as THE
        component), else None.  Injectable ``now`` makes this testable
        without sleeping."""
        now = monotonic_s() if now is None else now
        stalled: _Component | None = None
        stalled_over = 0.0
        with self._lock:
            comps = list(self._components.values())
        for c in comps:
            if c.idle or c.warned:
                continue
            budget = c.stall_after or self.stall_after
            over = (now - c.last_beat) - budget
            if over > 0 and over > stalled_over:
                stalled, stalled_over = c, over
        if stalled is None:
            return None
        stalled.warned = True  # one dump per stall; re-armed by beat()
        return {
            "component": stalled.name,
            "stalled_for_s": round(now - stalled.last_beat, 3),
            "stall_after_s": stalled.stall_after or self.stall_after,
            "components": self._snapshot(now),
            "alive_threads": sorted(
                t.name for t in threading.enumerate()
            ),
        }

    def _dump(self, diag: dict) -> None:
        # Perfetto marker (ISSUE 8 satellite): the stall is visible ON the
        # timeline at the instant it fired — lined up against whatever the
        # other tracks were (not) doing — instead of only in the JSONL
        # record and watchdog_stacks.txt.  No-op while tracing is off.
        trace.instant(
            "stall",
            component=diag["component"],
            stalled_for_s=diag["stalled_for_s"],
        )
        line = json.dumps({"event": "watchdog_stall", **diag})
        print(line, file=sys.stderr, flush=True)
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    f.write(line + "\n== thread stacks ==\n")
                    faulthandler.dump_traceback(file=f)
                    f.write("\n")
            except OSError:
                faulthandler.dump_traceback(file=sys.stderr)
        else:
            faulthandler.dump_traceback(file=sys.stderr)
        if self.sink is not None:
            try:
                self.sink.event("watchdog_stall", **diag)
            except Exception:
                pass  # a broken sink must not mask the stderr dump
        if self.on_stall is not None:
            self.on_stall(diag)

    # ---- poll thread -----------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.wait(self.poll_interval):
                diag = self.check_once()
                if diag is not None:
                    self._dump(diag)
        except BaseException as e:
            # The monitor must never die silently: a crashed poll thread
            # disarms stall diagnosis for the rest of the run, so announce
            # the disarm loudly before the thread ends.
            print(
                json.dumps({"event": "watchdog_crashed", "error": repr(e)}),
                file=sys.stderr, flush=True,
            )
            raise

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        # watchdog: the watchdog's own poll thread — it IS the monitor.
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-watchdog"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---- process-wide default instance --------------------------------------

_default = Watchdog()


def default() -> Watchdog:
    return _default


def register(
    name: str,
    stall_after: float | None = None,
    details: Callable[[], dict] | None = None,
) -> Heartbeat:
    """Register with the process-wide watchdog (always allowed; the poll
    thread may or may not be running — registration is just bookkeeping)."""
    return _default.register(name, stall_after=stall_after, details=details)


def start(
    stall_after: float | None = None,
    poll_interval: float | None = None,
    dump_path: str | None = None,
    sink: Any | None = None,
    on_stall: Callable[[dict], None] | None = None,
) -> Watchdog:
    """(Re)configure and start the default watchdog's poll thread."""
    if stall_after is not None:
        _default.stall_after = stall_after
    if poll_interval is not None:
        _default.poll_interval = poll_interval
    if dump_path is not None:
        _default.dump_path = dump_path
    if sink is not None:
        _default.sink = sink
    if on_stall is not None:
        _default.on_stall = on_stall
    _default.start()
    return _default


def stop() -> None:
    _default.stop()
