"""Perf-doctor CLI: ``python -m batchai_retinanet_horovod_coco_tpu.obs.analyze``.

Post-hoc analysis of any obs dir (the offline twin of the finalize-time
auto-emit — byte-identical output for the same artifacts), plus the
``--check`` mode behind ``make perf-report-check``: schema-validate the
fresh report and enforce an absolute regression band on the step-time
attribution fractions against the committed repo-root PERF_REPORT.json,
with bench-check's device-class guard (reports from different device
kinds are not comparable — a mismatch passes with a loud re-capture
note, never a false REGRESSION).

Exit codes: 0 ok, 1 schema problem / regression, 2 usage (missing
artifacts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from batchai_retinanet_horovod_coco_tpu.obs.analyze.report import (
    AnalyzeError,
    analyze_dir,
    analyze_fleet_dir,
    validate_report,
    write_report,
)

# Absolute per-fraction band for --check: attribution fractions move with
# host load far more than throughput does (a descheduled CPU smoke can
# shift data_wait by whole points), so the default band is generous; a
# real inversion — data_wait% doubling, step% collapsing — still trips it.
DEFAULT_BAND_ABS = 0.20


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    )


def _default_baseline() -> str:
    return os.path.join(_repo_root(), "PERF_REPORT.json")


def _summary_line(report: dict, path: str | None) -> str:
    steps = report.get("steps") or {}
    mfu = report.get("mfu") or {}
    top = [b["name"] for b in report.get("bottlenecks", [])]
    return json.dumps(
        {
            "perf_report": path,
            "device_kind": (report.get("source") or {}).get("device_kind"),
            "steps": steps.get("count"),
            "decomposition": steps.get("decomposition"),
            "mfu": mfu.get("mfu"),
            "top_bottlenecks": top,
        },
        sort_keys=True,
    )


def _check(fresh: dict, baseline_path: str, band: float) -> int:
    problems = validate_report(fresh)
    if problems:
        print(f"# perf-report-check: fresh report invalid: {problems}")
        return 1
    try:
        with open(baseline_path) as f:
            committed = json.load(f)
    except (OSError, ValueError) as e:
        print(
            f"# perf-report-check: cannot read committed baseline "
            f"{baseline_path!r}: {e}"
        )
        return 1
    problems = validate_report(committed)
    if problems:
        print(
            f"# perf-report-check: committed baseline invalid: {problems} "
            "— re-capture with `make perf-report-check` after fixing"
        )
        return 1
    fresh_dev = (fresh.get("source") or {}).get("device_kind")
    committed_dev = (committed.get("source") or {}).get("device_kind")
    if committed_dev != fresh_dev:
        # bench-check's device-class guard: fractions shift with the
        # host/device balance, so cross-class comparison is meaningless.
        print(
            f"# perf-report-check: committed report was captured on "
            f"{committed_dev!r} but this run is on {fresh_dev!r}; "
            "attribution fractions are not comparable across device "
            "classes — re-capture the baseline on this device"
        )
        return 0
    fresh_d = (fresh.get("steps") or {}).get("decomposition")
    committed_d = (committed.get("steps") or {}).get("decomposition")
    if not fresh_d or not committed_d:
        print(
            "# perf-report-check: a report has no step decomposition "
            "(no train loop in the trace?) — nothing to band-check"
        )
        return 1
    rc = 0
    for key in sorted(committed_d):
        got = float(fresh_d.get(key, 0.0))
        want = float(committed_d[key])
        delta = got - want
        verdict = "ok" if abs(delta) <= band else "REGRESSION"
        print(
            f"# perf-report-check: {key}: {got:.3f} vs committed "
            f"{want:.3f} (band ±{band:.2f}): {verdict}"
        )
        if verdict != "ok":
            rc = 1
    return rc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m batchai_retinanet_horovod_coco_tpu.obs.analyze",
        description="obs artifacts -> PERF_REPORT.json (the perf doctor)",
    )
    ap.add_argument("obs_dir", help="observability artifact directory "
                                    "(as left by an --obs-trace run)")
    ap.add_argument("--trace", default="trace.json",
                    help="trace file name inside obs_dir (bench runs "
                         "write bench_<mode>_trace.json)")
    ap.add_argument("--events", default="metrics.jsonl",
                    help="events JSONL name inside obs_dir (enrichment; "
                         "analysis proceeds without it)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode (ISSUE 15): the obs dir is a fleet "
                         "CLI run's — add the per-replica decomposition, "
                         "time-weighted routing share, breaker/canary/"
                         "re-dispatch timeline (from the merged trace) "
                         "and the FLEET_METRICS.json cross-reference, "
                         "with fleet verdicts (unavailable / most-shed / "
                         "slowest replica) ranked into the bottlenecks")
    ap.add_argument("--fleet-metrics", default="FLEET_METRICS.json",
                    help="federated metrics dump name inside obs_dir "
                         "(--fleet mode; analysis proceeds without it)")
    ap.add_argument("--out", default=None,
                    help="report path (default <obs_dir>/PERF_REPORT.json)")
    ap.add_argument("--print", action="store_true", dest="print_report",
                    help="print the full report to stdout as well")
    ap.add_argument("--check", nargs="?", const="", default=None,
                    metavar="BASELINE",
                    help="perf-report-check mode: schema-validate and "
                         "enforce the attribution-fraction band against "
                         "BASELINE (default: the committed repo-root "
                         "PERF_REPORT.json)")
    ap.add_argument("--band", type=float,
                    default=float(
                        os.environ.get("PERF_BAND_ABS", str(DEFAULT_BAND_ABS))
                    ),
                    help="absolute per-fraction band for --check "
                         "(env PERF_BAND_ABS)")
    args = ap.parse_args(argv)

    try:
        if args.fleet:
            report = analyze_fleet_dir(
                args.obs_dir, trace_name=args.trace,
                events_name=args.events,
                metrics_name=args.fleet_metrics,
            )
        else:
            report = analyze_dir(
                args.obs_dir, trace_name=args.trace,
                events_name=args.events,
            )
    except AnalyzeError as e:
        print(f"# obs.analyze: {e}", file=sys.stderr)
        print(
            "# obs.analyze: run a traced workload first, e.g. "
            "`python train.py ... --obs-trace --obs-dir <dir>`",
            file=sys.stderr,
        )
        return 2

    out = args.out or os.path.join(args.obs_dir, "PERF_REPORT.json")
    write_report(report, out)
    if args.print_report:
        print(json.dumps(report, indent=2, sort_keys=True))
    print(_summary_line(report, out))

    if args.check is not None:
        return _check(report, args.check or _default_baseline(), args.band)
    problems = validate_report(report)
    if problems:
        print(f"# obs.analyze: report failed schema validation: {problems}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
