"""Perf doctor (ISSUE 8): the obs subsystem's read side.

``report`` turns a run's own artifacts (merged ``trace.json``, events
JSONL via ``split_runs``, watchdog markers) into one machine-readable
``PERF_REPORT.json`` — step-time decomposition, pipeline overlap
efficiency, queue/stall correlation, memory trend, an MFU estimate from
the recorded XLA cost-analysis FLOPs, and a ranked top-3 bottleneck
verdict naming the spans and ``tune/`` problems to attack next.

Three entrypoints:

- inline auto-emit at ``train.py``/``bench.py`` finalize (``auto_emit``
  — never raises; failure is one structured event);
- offline CLI: ``python -m batchai_retinanet_horovod_coco_tpu.obs.analyze
  <obs_dir>`` (byte-identical to the inline report for the same dir);
- ``make perf-report`` / ``make perf-report-check`` (schema validation +
  regression band on the attribution fractions vs the committed
  PERF_REPORT.json, bench-check's device-class guard).

jax-free: the analyzer reads artifacts, never devices.
"""

from batchai_retinanet_horovod_coco_tpu.obs.analyze.report import (
    AnalyzeError,
    CPU_NOMINAL_PEAK_TFLOPS,
    PEAK_TFLOPS,
    SCHEMA_VERSION,
    analyze_dir,
    analyze_events,
    auto_emit,
    device_peak_tflops,
    load_trace,
    span_attribution,
    validate_report,
    write_report,
)

__all__ = [
    "AnalyzeError",
    "CPU_NOMINAL_PEAK_TFLOPS",
    "PEAK_TFLOPS",
    "SCHEMA_VERSION",
    "analyze_dir",
    "analyze_events",
    "auto_emit",
    "device_peak_tflops",
    "load_trace",
    "span_attribution",
    "validate_report",
    "write_report",
]
